"""Activation recomputation (gradient checkpointing).

Reference: fleet/recompute/recompute.py — RecomputeFunction (:108, PyLayer
that reruns forward under saved RNG state), recompute() (:404),
recompute_hybrid.py (PP variant with the mp RNG tracker).

TPU-native: under tracing (to_static / program-level grad) this is
``jax.checkpoint`` — XLA rematerializes inside the single program, which is
both the idiomatic and the faster form (no Python re-entry). In pure eager
mode the tape stores op *inputs* per node; recompute wraps the block so only
the block inputs are retained and the inner tape is rebuilt at backward.
"""

from __future__ import annotations

import jax

from ....core import rng as rng_mod, state
from ....core.engine import Edge, GradNode, run_backward
from ....core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential", "recompute_hybrid",
           "RecomputeFunction"]


def _eager_recompute(function, args, kwargs, preserve_rng_state=True):
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    requires_grad = state.grad_enabled() and any(
        not t.stop_gradient for t in tensor_args)
    rng_before = rng_mod.DEFAULT_GENERATOR.get_state()
    with state.no_grad_guard():
        out = function(*args, **kwargs)
    if not requires_grad:
        return out
    out_is_tuple = isinstance(out, (list, tuple))
    outs = tuple(out) if out_is_tuple else (out,)
    detached_args = [a.detach() if isinstance(a, Tensor) else a for a in args]

    def bwd(primals, cts):
        cts_list = list(cts) if isinstance(cts, tuple) else [cts]
        if preserve_rng_state:
            rng_now = rng_mod.DEFAULT_GENERATOR.get_state()
            rng_mod.DEFAULT_GENERATOR.set_state(rng_before)
        try:
            inner_args = []
            grad_inputs = []
            for a in detached_args:
                if isinstance(a, Tensor):
                    t = Tensor._wrap(a._data)
                    t.stop_gradient = False
                    inner_args.append(t)
                    grad_inputs.append(t)
                else:
                    inner_args.append(a)
            with state.enable_grad_guard():
                inner_out = function(*inner_args, **kwargs)
            inner_outs = (tuple(inner_out) if isinstance(inner_out,
                                                         (list, tuple))
                          else (inner_out,))
            capture = {id(t): t for t in grad_inputs}
            captured = run_backward(
                [o for o in inner_outs],
                [Tensor._wrap(c) for c in cts_list],
                capture=capture, accumulate_others=True)
            # align captured grads with args order
            gi = iter(grad_inputs)
            out_grads = []
            for a in args:
                if isinstance(a, Tensor):
                    t = next(gi)
                    g = captured.get(id(t))
                    out_grads.append(g)
                else:
                    out_grads.append(None)
            return tuple(out_grads)
        finally:
            if preserve_rng_state:
                rng_mod.DEFAULT_GENERATOR.set_state(rng_now)

    edges = [Edge.from_tensor(a) if isinstance(a, Tensor) else Edge(stop=True)
             for a in args]
    out_avals = [(tuple(o._data.shape), o._data.dtype) for o in outs]
    node = GradNode("recompute", lambda primals, cts: bwd(primals, cts), (),
                    edges, out_avals, out_is_tuple)
    new_outs = []
    for i, o in enumerate(outs):
        t = Tensor._wrap(o._data)
        t.stop_gradient = False
        t._node = node
        t._out_idx = i
        new_outs.append(t)
    return (type(out)(new_outs) if out_is_tuple else new_outs[0])


def recompute(function, *args, **kwargs):
    """Reference recompute.py:404."""
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    if state.in_trace():
        # inside to_static / program grad: use XLA remat
        from ....utils.functional_call import functional_call

        tensor_mask = [isinstance(a, Tensor) for a in args]
        arrays = [a._data if isinstance(a, Tensor) else a for a in args]

        @jax.checkpoint
        def inner(*arrs):
            rebuilt = [Tensor._wrap(a) if m else a
                       for a, m in zip(arrs, tensor_mask)]
            out = function(*rebuilt, **kwargs)
            return jax.tree.map(
                lambda o: o._data if isinstance(o, Tensor) else o, out,
                is_leaf=lambda o: isinstance(o, Tensor))

        out = inner(*arrays)
        return jax.tree.map(Tensor._wrap, out)
    return _eager_recompute(function, args, kwargs, preserve)


def recompute_sequential(ctx, functions, *args, **kwargs):
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    seg_size = max(len(funcs) // max(segments, 1), 1)

    def run_segment(fs):
        def seg_fn(*a, **kw):
            out = a[0] if len(a) == 1 else a
            for f in fs:
                out = f(out)
            return out

        return seg_fn

    out = args[0] if len(args) == 1 else args
    for start in range(0, len(funcs), seg_size):
        fs = funcs[start : start + seg_size]
        out = recompute(run_segment(fs), out, **kwargs)
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """reference recompute_hybrid.py — PP variant; RNG-tracker handling is
    subsumed by preserve_rng_state."""
    return recompute(function, *args, **kwargs)


RecomputeFunction = recompute
