"""paddle.distributed.fleet (reference: python/paddle/distributed/fleet/
__init__.py — module-level functions delegate to the Fleet singleton)."""

from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from .fleet import Fleet, fleet_singleton as _fleet  # noqa: F401
from . import utils  # noqa: F401
from . import meta_optimizers  # noqa: F401
from .base.role_maker import (  # noqa: F401
    PaddleCloudRoleMaker, Role, UserDefinedRoleMaker, UtilBase,
)
from .data_generator import (  # noqa: F401
    MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    return _fleet.init(role_maker, is_collective, strategy, log_level)


def distributed_model(model):
    return _fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return _fleet.distributed_optimizer(optimizer, strategy)


def distributed_scaler(scaler):
    return _fleet.distributed_scaler(scaler)


def get_hybrid_communicate_group():
    return _fleet.get_hybrid_communicate_group()


def worker_index():
    return _fleet.worker_index()


def worker_num():
    return _fleet.worker_num()


def is_first_worker():
    return _fleet.is_first_worker()


def barrier_worker():
    return _fleet.barrier_worker()


def init_worker():
    return _fleet.init_worker()


def init_server(*args, **kwargs):
    return _fleet.init_server(*args, **kwargs)


def stop_worker():
    return _fleet.stop_worker()


def get_strategy():
    return _fleet.strategy
