"""Fleet orchestration singleton.

Reference: python/paddle/distributed/fleet/fleet.py — Fleet.init (:167),
_init_hybrid_parallel_env (:603), distributed_optimizer (:1306);
model wrapping in fleet/model.py:32.
"""

from __future__ import annotations

import os

import jax

from .. import init_parallel_env
from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["Fleet", "fleet_singleton"]


class _RoleMaker:
    """PaddleCloudRoleMaker analog: rank/size come from jax.distributed."""

    def __init__(self):
        self._rank = jax.process_index()
        self._size = max(jax.process_count(), 1)

    def worker_index(self):
        return self._rank

    def worker_num(self):
        return self._size

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return self._rank == 0


class Fleet:
    def __init__(self):
        self._is_initialized = False
        self._hcg: HybridCommunicateGroup | None = None
        self._user_defined_strategy = DistributedStrategy()
        self._role_maker = None

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        init_parallel_env()
        self._role_maker = role_maker or _RoleMaker()
        if strategy is not None:
            self._user_defined_strategy = strategy
        self._is_initialized = True
        hc = self._user_defined_strategy.hybrid_configs
        degrees = [hc.get("dp_degree", 1), hc.get("pp_degree", 1),
                   hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
                   hc.get("mp_degree", 1)]
        # -1 => fill with remaining devices
        total = jax.device_count()
        try:
            total = max(total, len(jax.devices("cpu")))
        except RuntimeError:
            pass
        known = 1
        for d in degrees:
            if d > 0:
                known *= d
        degrees = [total // known if d == -1 else d for d in degrees]
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "sep", "model"], degrees)
        self._hcg = HybridCommunicateGroup(topo)
        return self

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return True

    def barrier_worker(self):
        pass

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        assert self._hcg is not None, "call fleet.init first"
        return self._hcg

    @property
    def strategy(self):
        return self._user_defined_strategy

    # ---- wrapping ----
    def distributed_model(self, model):
        """Reference fleet/model.py:32 — picks the wrapper by strategy."""
        from ..meta_parallel.meta_parallel_base import wrap_distributed_model

        return wrap_distributed_model(model, self._hcg,
                                      self._user_defined_strategy)

    def distributed_optimizer(self, optimizer, strategy=None):
        """Reference fleet.py:1306 → HybridParallelOptimizer."""
        from ..meta_parallel.hybrid_parallel_optimizer import (
            HybridParallelOptimizer,
        )

        if strategy is not None:
            self._user_defined_strategy = strategy
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       self._user_defined_strategy)

    def distributed_scaler(self, scaler):
        return scaler

    # PS-mode entry points (recommendation path) — collective-only build
    def init_worker(self):
        pass

    def init_server(self, *args, **kwargs):
        pass

    def run_server(self):
        raise NotImplementedError(
            "parameter-server mode: the sparse-embedding path is served by "
            "sharded embeddings (incubate.sharded_embedding); brpc PS has no "
            "TPU analog (SURVEY.md §7.3 item 4)")

    def stop_worker(self):
        pass

    def save_inference_model(self, *args, **kwargs):
        raise NotImplementedError("use paddle.jit.save")

    def save_persistables(self, executor, dirname, main_program=None):
        raise NotImplementedError("use paddle.save")


fleet_singleton = Fleet()
