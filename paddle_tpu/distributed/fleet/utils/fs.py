"""Filesystem clients for fleet checkpoint/data plumbing.

Reference: python/paddle/distributed/fleet/utils/fs.py — ``FS`` (abstract),
``LocalFS`` (host filesystem) and ``HDFSClient`` (hadoop CLI wrapper).
``LocalFS`` is fully functional; ``HDFSClient`` shells out to the hadoop
binary when one is configured and raises a clear error otherwise (TPU pods
normally mount GCS/NFS paths that LocalFS covers directly).
"""

from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["LocalFS", "HDFSClient"]


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    """Abstract filesystem interface (reference fs.py FS)."""

    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False, test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Host filesystem client (reference fs.py LocalFS). ``rename``/``mv``
    (the checkpoint-publish operations) retry transient OSErrors with the
    shared exponential-backoff shape (``FLAGS_ckpt_save_retries``) — on NFS
    and FUSE mounts a rename can fail transiently under server load — and
    carry the ``fs.rename`` fault-injection site. ``upload``/``download``
    publish through ``utils.retry.atomic_copy`` (tmp → fsync → rename), so
    a killed copy can never leave a torn destination visible — the same
    guarantee ``rename`` already had. Listings are SORTED: the streaming
    data plane derives its shard→rank assignment from ``ls_dir``, and
    readdir order is filesystem-dependent (ext4 hash order vs HDFS
    lexicographic), so an unsorted listing would silently train different
    data per platform."""

    def ls_dir(self, fs_path):
        """(dirs, files) directly under ``fs_path``, each sorted."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for entry in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, entry)):
                dirs.append(entry)
            else:
                files.append(entry)
        return dirs, files

    def mkdirs(self, fs_path):
        assert not os.path.isfile(fs_path), f"{fs_path} is already a file"
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        from ....utils import fault_injection
        from ....utils.retry import replace_across_fs, retry_os

        def attempt():
            fault_injection.fire("fs.rename")
            # replace_across_fs: atomic same-fs rename, with a copy+fsync+
            # replace fallback when src and dst sit on different mounts
            # (EXDEV is deterministic — retrying it would burn the whole
            # backoff budget and then fail anyway)
            replace_across_fs(fs_src_path, fs_dst_path)

        retry_os(attempt)

    def _rmr(self, fs_path):
        shutil.rmtree(fs_path)

    def _rm(self, fs_path):
        os.remove(fs_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path):
            return self._rm(fs_path)
        return self._rmr(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        from ....utils.retry import atomic_write, retry_os

        # atomic empty-file publication: the path either exists complete
        # (trivially, for an empty file) or not at all — uniform with the
        # other write paths, and safe for sentinel-file callers
        retry_os(lambda: atomic_write(fs_path, lambda f: None))

    def upload(self, local_path, fs_path):
        """Copy ``local_path`` into the filesystem at ``fs_path``
        atomically: a crash mid-copy leaves no torn file visible."""
        if not os.path.exists(local_path):
            raise FSFileNotExistsError(local_path)
        from ....utils.retry import atomic_copy, retry_os

        retry_os(lambda: atomic_copy(local_path, fs_path))

    def download(self, fs_path, local_path):
        """Copy ``fs_path`` out to ``local_path`` atomically (same
        contract as :meth:`upload`, mirrored)."""
        if not self.is_exist(fs_path):
            raise FSFileNotExistsError(fs_path)
        from ....utils.retry import atomic_copy, retry_os

        retry_os(lambda: atomic_copy(fs_path, local_path))

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        if self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        return self.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        """All sub-directory names directly under ``fs_path``, sorted."""
        if not self.is_exist(fs_path):
            return []
        return [entry for entry in sorted(os.listdir(fs_path))
                if os.path.isdir(os.path.join(fs_path, entry))]


class HDFSClient(FS):
    """Hadoop CLI wrapper (reference fs.py HDFSClient). Requires a local
    hadoop installation; every call shells out to ``hadoop fs``."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._base_cmd = [os.path.join(hadoop_home, "bin/hadoop"), "fs"]
        if configs:
            for k, v in configs.items():
                self._base_cmd += ["-D", f"{k}={v}"]
        self._time_out = time_out

    def _run(self, *args):
        cmd = self._base_cmd + list(args)
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=max(1, self._time_out // 1000))
        except FileNotFoundError as e:
            raise ExecuteError(
                f"hadoop binary not found ({cmd[0]}); HDFSClient needs a "
                "local hadoop install — use LocalFS for host/NFS/GCS-mount "
                "paths") from e
        except subprocess.TimeoutExpired as e:
            # keep the fs contract: callers handle ExecuteError, never a
            # raw subprocess exception
            raise ExecuteError(
                f"{' '.join(cmd)} timed out after {self._time_out}ms") from e
        if proc.returncode != 0:
            raise ExecuteError(f"{' '.join(cmd)} failed: {proc.stderr}")
        return proc.stdout

    def ls_dir(self, fs_path):
        """(dirs, files), each sorted — the FS-parity contract with
        LocalFS (hadoop already lists lexicographically, but the sort
        makes the determinism explicit rather than inherited)."""
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            fields = line.split()
            if len(fields) < 8:
                continue
            name = os.path.basename(fields[-1])
            (dirs if fields[0].startswith("d") else files).append(name)
        return sorted(dirs), sorted(files)

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def upload(self, local_path, fs_path, multi_processes=1, overwrite=False):
        if overwrite:
            self._run("-put", "-f", local_path, fs_path)
        else:
            self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path, multi_processes=1,
                 overwrite=False):
        if overwrite and os.path.exists(local_path):
            # hadoop -get refuses existing targets; honor overwrite locally
            if os.path.isdir(local_path):
                import shutil

                shutil.rmtree(local_path)
            else:
                os.remove(local_path)
        self._run("-get", fs_path, local_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self.is_exist(fs_path):
            self._run("-rm", "-r", fs_path)

    def need_upload_download(self):
        return True

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(fs_src_path)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        elif self.is_exist(fs_dst_path):
            # `hadoop fs -mv` onto an existing dir silently nests the
            # source inside it; enforce the FS contract instead
            raise FSFileExistsError(fs_dst_path)
        self.rename(fs_src_path, fs_dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        self._run("-touchz", fs_path)
