"""fleet.utils (reference: fleet/utils/__init__.py)."""

from . import sequence_parallel_utils  # noqa: F401
from ..recompute import recompute, recompute_sequential  # noqa: F401


class HybridParallelInferenceHelper:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "distributed inference: use paddle.jit.save + sharded load")
