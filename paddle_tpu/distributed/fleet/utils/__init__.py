"""fleet.utils (reference: fleet/utils/__init__.py)."""

from . import sequence_parallel_utils  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401
from ..recompute import (recompute, recompute_hybrid,  # noqa: F401
                         recompute_sequential)

__all__ = ["LocalFS", "recompute", "DistributedInfer", "HDFSClient",
           "recompute_sequential", "recompute_hybrid"]


class DistributedInfer:
    """Reference: fleet/utils/ps_util.py DistributedInfer — rewires a
    parameter-server training program for distributed inference. The PS
    pull/push machinery it patches does not exist on this framework."""

    def __init__(self, main_program=None, startup_program=None):
        raise NotImplementedError(
            "DistributedInfer targets the parameter-server inference path; "
            "use paddle.jit.save + sharded load (distributed.checkpoint) "
            "for distributed inference on this framework")


class HybridParallelInferenceHelper:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "distributed inference: use paddle.jit.save + sharded load")
