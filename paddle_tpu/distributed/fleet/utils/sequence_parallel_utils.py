"""Sequence-parallel utilities.

Reference: fleet/utils/sequence_parallel_utils.py — ScatterOp/GatherOp/
AllGatherOp/ReduceScatterOp PyLayers (:38-145), mark_as_sequence_parallel_parameter,
ColumnSequenceParallelLinear (:230), RowSequenceParallelLinear (:340).

TPU-native: sequence parallelism = the sequence dim of activations sharded
over the 'mp' mesh axis (Megatron-SP rides the TP group). The PyLayer pairs
become sharding constraints; GSPMD emits the all_gather before the column
matmul and the reduce_scatter after the row matmul.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn.initializer import XavierUniform
from ....nn.layer.layers import Layer

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "scatter", "all_gather", "mark_as_sequence_parallel_parameter",
    "is_sequence_parallel_parameter", "ColumnSequenceParallelLinear",
    "RowSequenceParallelLinear", "GatherOp_backward",
    "register_sequence_parallel_allreduce_hooks",
]


def _mesh_mp():
    from ..fleet import fleet_singleton

    try:
        hcg = fleet_singleton.get_hybrid_communicate_group()
        return hcg.mesh, hcg.get_model_parallel_world_size()
    except Exception:
        return None, 1


def _constrain_seq(t, seq_axis, sharded):
    mesh, mp = _mesh_mp()
    if mesh is None or mp <= 1 or not isinstance(t._data, jax.core.Tracer):
        return t
    spec = [None] * t.ndim
    if sharded:
        spec[seq_axis] = "mp"
    arr = jax.lax.with_sharding_constraint(t._data,
                                           NamedSharding(mesh, P(*spec)))
    out = Tensor._wrap(arr)
    out.stop_gradient = t.stop_gradient
    return out


def scatter(input, seq_axis=0):
    """sequence dim -> sharded over mp (reference ScatterOp fwd)."""
    return _constrain_seq(input, seq_axis, sharded=True)


def all_gather(input, seq_axis=0):
    """sequence dim -> replicated (reference AllGatherOp fwd)."""
    return _constrain_seq(input, seq_axis, sharded=False)


class ScatterOp:
    @staticmethod
    def apply(input, seq_axis=0):
        return scatter(input, seq_axis)


class GatherOp:
    @staticmethod
    def apply(input, seq_axis=0):
        return all_gather(input, seq_axis)


class AllGatherOp:
    @staticmethod
    def apply(input):
        return all_gather(input, 0)


class ReduceScatterOp:
    @staticmethod
    def apply(input):
        return scatter(input, 0)


GatherOp_backward = ReduceScatterOp


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """Grad sync of SP params over the mp group — automatic under GSPMD
    (gradients of replicated params are psum'd by the compiler); kept for API
    parity."""
    return model


class ColumnSequenceParallelLinear(Layer):
    """reference :230 — input arrives sequence-sharded, all_gather(seq) then
    column-parallel matmul."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        from ...meta_parallel.parallel_layers.mp_layers import _shard_param

        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if has_bias else None)
        self.gather_output = gather_output
        _shard_param(self.weight, (None, "mp"))

    def forward(self, x):
        x = all_gather(x, seq_axis=0)  # [s/mp, b, h] -> [s, b, h]
        out = F.linear(x, self.weight, self.bias)
        from ...meta_parallel.parallel_layers.mp_layers import _constrain

        if not self.gather_output:
            return _constrain(out, (None,) * (out.ndim - 1) + ("mp",))
        return out


class RowSequenceParallelLinear(Layer):
    """reference :340 — row-parallel matmul then reduce_scatter over the
    sequence dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        from ...meta_parallel.parallel_layers.mp_layers import _shard_param

        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if has_bias else None)
        self.input_is_parallel = input_is_parallel
        _shard_param(self.weight, ("mp", None))

    def forward(self, x):
        from ...meta_parallel.parallel_layers.mp_layers import _constrain

        if self.input_is_parallel:
            x = _constrain(x, (None,) * (x.ndim - 1) + ("mp",))
        out = F.linear(x, self.weight, self.bias)
        return scatter(out, seq_axis=0)
