"""Role makers + UtilBase (reference
python/paddle/distributed/fleet/base/role_maker.py and util_factory.py).

Under single-controller JAX the "role" is derived from the launch env
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM, set by distributed.launch or the
cloud scheduler); PS roles map onto the collective PS path
(distributed/ps) so every role maker reports TRAINER unless the env
declares a server list.
"""

from __future__ import annotations

import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "UtilBase"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def _worker_index(self):
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def _worker_num(self):
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def _is_first_worker(self):
        return self._worker_index() == 0

    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    def _server_num(self):
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        return len([e for e in eps.split(",") if e]) if eps else 0

    def _role_id(self):
        return self._worker_index()

    # public aliases the reference exposes through Fleet
    worker_index = _worker_index
    worker_num = _worker_num
    is_first_worker = _is_first_worker
    is_worker = _is_worker
    is_server = _is_server
    server_num = _server_num


class PaddleCloudRoleMaker(RoleMakerBase):
    """reference role_maker.py PaddleCloudRoleMaker: roles from the
    PaddleCloud/k8s env variables."""

    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        training_role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = (Role.SERVER if training_role == "PSERVER"
                      else Role.WORKER)

    def _generate_role(self):
        return self._role


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference UserDefinedRoleMaker: explicit role/rank/size instead of
    env sniffing."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective)
        self._kwargs = kwargs
        self._role = kwargs.get("role", Role.WORKER)
        if "current_id" in kwargs:
            os.environ["PADDLE_TRAINER_ID"] = str(kwargs["current_id"])
        if "worker_num" in kwargs:
            os.environ["PADDLE_TRAINERS_NUM"] = str(kwargs["worker_num"])


class UtilBase:
    """reference util_factory.py UtilBase: small cross-worker utilities.
    Collectives ride the in-process group (single-controller: world of
    one unless launched multi-process)."""

    def __init__(self):
        self.role_maker = PaddleCloudRoleMaker()

    def _set_role_maker(self, rm):
        self.role_maker = rm

    def get_file_shard(self, files):
        """Split a file list evenly over workers (reference
        UtilBase.get_file_shard)."""
        n = self.role_maker._worker_num()
        i = self.role_maker._worker_index()
        per, rem = divmod(len(files), n)
        start = i * per + min(i, rem)
        return files[start:start + per + (1 if i < rem else 0)]

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from ... import communication as _comm  # noqa: F401

        return np.asarray(input)  # world-of-one: identity; multi-process
        # reductions go through paddle.distributed.all_reduce on tensors

    def barrier(self, comm_world="worker"):
        return None

    def all_gather(self, input, comm_world="worker"):
        return [input]

    def print_on_rank(self, message, rank_id=0):
        if self.role_maker._worker_index() == int(rank_id):
            print(message)
