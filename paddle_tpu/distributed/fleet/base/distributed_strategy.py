"""DistributedStrategy.

Reference: python/paddle/distributed/fleet/base/distributed_strategy.py:175
(protobuf-backed, paddle/fluid/framework/distributed_strategy.proto:359 —
HybridConfig :95, ShardingConfig :41, AMPConfig :106, RecomputeConfig :33).
Here a plain typed config object with the same field surface.
"""

from __future__ import annotations

__all__ = ["DistributedStrategy"]


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees (HybridConfig)
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
            "mp_configs": {},
            "pp_configs": {},
        }
        # feature toggles mirroring the proto
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "use_dynamic_loss_scaling": True,
            "custom_white_list": [],
            "custom_black_list": [],
            "use_pure_fp16": False,
            "use_bf16": True,
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"sharding_degree": 1, "stage": 1}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.find_unused_parameters = False
        self.heter_ccl_mode = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.a_sync = False
        self.a_sync_configs = {}
        self.auto = False
        self.semi_auto = False

    # paddle exposes attribute-style set/get with validation; keep permissive
    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()}
        return f"DistributedStrategy({fields})"
