"""Hybrid-parallel topology.

Reference: python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology (:61, axes ["data","pipe","sharding","sep","model"]) and
HybridCommunicateGroup (:174) which creates NCCL comms per axis.

TPU-native redesign: the topology IS a jax device Mesh with axes
("dp", "pp", "sharding", "sep", "mp"); per-axis "groups" are axis views
(collective.Group with axis_name) — no communicator creation, XLA compiles
collectives onto ICI from the mesh. The paddle axis names data/pipe/model map
to dp/pp/mp mesh axis names (shard_map axis names must match what the
meta-parallel layers use).
"""

from __future__ import annotations

import itertools

import numpy as np
import jax

from ...collective import Group, new_group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

_PADDLE2MESH = {"data": "dp", "pipe": "pp", "sharding": "sharding",
                "sep": "sep", "model": "mp"}


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep",
                                           "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self.coordinate = None
        self._world_size = int(np.prod(self._dims))
        ranks = np.arange(self._world_size).reshape(self._dims)
        self._rank_array = ranks
        self._coord_of_rank = {
            int(ranks[c]): c for c in itertools.product(
                *[range(d) for d in self._dims])
        }

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return int(self._rank_array[coord])

    def get_coord(self, rank):
        from collections import namedtuple

        Coord = namedtuple("Coord", self._parallel_names)
        return Coord(*self._coord_of_rank[rank])

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._rank_array, axis, 0)
        return moved[index].reshape(-1).tolist()

    def get_comm_list(self, axis_name):
        """All rank-groups along axis_name."""
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._rank_array, axis, -1)
        return moved.reshape(-1, self._dims[axis]).tolist()

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)._asdict()
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        self.nranks = topology.world_size()
        self.global_rank = jax.process_index() if jax.process_count() > 1 else 0

        # Build the global device mesh with mesh-axis names (dp/pp/...)
        mesh_names = tuple(_PADDLE2MESH.get(n, n) for n in names)
        devs = jax.devices()
        if len(devs) < self.nranks:
            try:
                cpus = jax.devices("cpu")
                if len(cpus) >= self.nranks:
                    devs = cpus
            except RuntimeError:
                pass
        assert len(devs) >= self.nranks, (
            f"topology needs {self.nranks} devices, have {len(devs)}")
        mesh_devs = np.array(devs[: self.nranks], dtype=object).reshape(dims)
        self._mesh = jax.sharding.Mesh(
            mesh_devs, mesh_names,
            axis_types=(jax.sharding.AxisType.Auto,) * len(mesh_names))

        self._dp_degree = topology.get_dim("data")
        self._mp_degree = topology.get_dim("model")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") if "sep" in names else 1

        coord = topology.get_coord(self.global_rank)

        def make_group(axis):
            ranks = topology.get_axis_list(
                axis, getattr(coord, axis) if False else 0)
            # per-rank group membership: ranks sharing all other coords
            my = coord._asdict()
            groups = topology.get_comm_list(axis)
            mine = next(g for g in groups if self.global_rank in g)
            return new_group(mine, axis_name=_PADDLE2MESH.get(axis, axis),
                             mesh=self._mesh)

        self._dp_group = make_group("data")
        self._mp_group = make_group("model")
        self._pp_group = make_group("pipe")
        self._sharding_group = make_group("sharding")
        self._sep_group = make_group("sep") if "sep" in names else None
        self._check_group = new_group(list(range(self.nranks)),
                                      axis_name=None, mesh=self._mesh)

    # ---- mesh access (TPU-native extension) ----
    @property
    def mesh(self) -> jax.sharding.Mesh:
        return self._mesh

    def topology(self):
        return self._topo

    def get_hybrid_communicate_group(self):
        return self

    # ---- data parallel ----
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).data

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    # ---- model (tensor) parallel ----
    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).model

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # ---- pipeline ----
    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_stage_id(self):
        return self._topo.get_coord(self.global_rank).pipe

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    # ---- sharding ----
    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).sharding

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    # ---- sep ----
    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_rank(self):
        c = self._topo.get_coord(self.global_rank)
        return getattr(c, "sep", 0)

    def get_sep_parallel_group(self):
        return self._sep_group

    # ---- misc ----
    def get_check_parallel_group(self, sharding=False):
        return self._check_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)
