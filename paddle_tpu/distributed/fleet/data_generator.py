"""MultiSlot data generators (reference
python/paddle/distributed/fleet/data_generator/data_generator.py).

These produce the line protocol the PS DataFeed consumes: per slot,
``n_values v1 ... vn`` (counts then values, space-joined across slots —
slot NAMES are schema, not wire data). The TPU path trains from
DataLoaders, but PaddleRec-style pipelines call these generators to
preprocess text streams — the protocol is preserved so those scripts run
unchanged (InMemoryDataset/QueueDataset parse_fn can consume the output).
"""

from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 1

    def set_batch(self, batch_size):
        self.batch_size_ = int(batch_size)

    def generate_sample(self, line):
        """User override: line -> iterator of (slot_name, values) lists."""
        raise NotImplementedError(
            "implement generate_sample(self, line) returning an iterator")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _format(self, record):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            for record in self._records_of(line):
                sys.stdout.write(self._format(record))

    def _records_of(self, line):
        gen = self.generate_sample(line)
        out = []
        for record in gen():
            out.append(record)
        return out

    def run_from_memory(self):
        """Test/offline hook: returns the formatted lines instead of
        streaming stdin->stdout."""
        raise NotImplementedError


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slot values; wire line per record: ``len v1 v2 ...`` per
    slot, space-joined (reference _gen_str of MultiSlotDataGenerator)."""

    def _format(self, record):
        parts = []
        for name, values in record:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"

    def generate_lines(self, lines):
        return [self._format(r) for line in lines
                for r in self._records_of(line)]


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-valued variant — same wire format, values pass through as
    strings (reference MultiSlotStringDataGenerator)."""
