"""LocalSGD: k local optimizer steps per data-parallel replica, then a
parameter average across the dp axis.

Reference: python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py
(LocalSGDOptimizer: snapshot vars + broadcast-averaged params every
``k_steps``; AdaptiveLocalSGDOptimizer adjusts k from loss).

TPU-native design
-----------------
The reference rewrites a static program so each NCCL rank steps its private
parameter copy and periodically allreduce-averages them. Under
single-controller SPMD there are no private rank copies — parameters are one
logical array — so divergent replicas must be *modelled explicitly*: every
parameter leaf carries a leading replica dimension of size R sharded over the
``dp`` mesh axis, and the whole cycle (k grad steps on the replica's own
microbatches, then ``lax.pmean`` over dp) runs inside one compiled
``shard_map``. XLA emits exactly one all-reduce per sync boundary — the same
communication volume the reference achieves, with the k local steps fused
into the same executable instead of k eager rounds.

Used via ``fleet.DistributedStrategy().localsgd`` semantics or directly:

    stepper = LocalSGD(mesh, axis="dp", k_steps=4, learning_rate=0.1)
    step = stepper.build(loss_fn)            # jitted
    stacked = stepper.replicate(params)      # [R, ...] leaves
    stacked, loss = step(stacked, batches)   # batches: [R, k, ...]
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["LocalSGD"]


class LocalSGD:
    """Compiled LocalSGD cycle over a named mesh axis.

    Args:
        mesh: ``jax.sharding.Mesh`` containing ``axis``.
        axis: mesh axis name the replicas ride (default ``"dp"``).
        k_steps: local steps between parameter averages (reference
            ``localsgd_configs["k_steps"]``).
        learning_rate: SGD step size for the local updates.
    """

    def __init__(self, mesh, axis="dp", k_steps=1, learning_rate=0.01):
        self.mesh = mesh
        self.axis = axis
        self.k_steps = int(k_steps)
        self.lr = float(learning_rate)
        self.n_replicas = mesh.shape[axis]

    @classmethod
    def from_strategy(cls, strategy, mesh, axis="dp", learning_rate=0.01):
        """Build from ``DistributedStrategy.localsgd_configs`` (reference
        localsgd_optimizer.py reads k_steps the same way)."""
        cfg = getattr(strategy, "localsgd_configs", None) or {}
        return cls(mesh, axis=axis, k_steps=cfg.get("k_steps", 1),
                   learning_rate=learning_rate)

    def replicate(self, params):
        """Broadcast a params pytree to the stacked [R, ...] layout, sharded
        over the dp axis (every replica starts from the same point, as the
        reference's init broadcast does)."""
        r = self.n_replicas
        stacked = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (r,) + p.shape), params)
        sh = NamedSharding(self.mesh, P(self.axis))
        return jax.tree.map(lambda a: jax.device_put(a, sh), stacked)

    def build(self, loss_fn, sync=True):
        """Return a jitted ``step(stacked_params, stacked_batches)``.

        ``loss_fn(params, batch) -> scalar``. ``stacked_batches`` leaves are
        ``[R, k_steps, ...]`` — each replica consumes its own k microbatches.
        With ``sync=False`` the boundary average is skipped (used by tests to
        observe replica divergence mid-cycle, and by the adaptive variant).
        """
        lr, k, axis = self.lr, self.k_steps, self.axis

        def per_replica(params, batches):
            # leading replica dim is size 1 inside the shard; drop it
            params = jax.tree.map(lambda a: a[0], params)
            batches = jax.tree.map(lambda a: a[0], batches)

            def one(i, carry):
                ps, acc = carry
                mb = jax.tree.map(lambda a: a[i], batches)
                l, g = jax.value_and_grad(loss_fn)(ps, mb)
                ps = jax.tree.map(lambda p, gg: p - lr * gg, ps, g)
                return ps, acc + l

            acc0 = jax.lax.pcast(jnp.float32(0.0), (axis,), to="varying")
            params, loss_sum = jax.lax.fori_loop(0, k, one, (params, acc0))
            if sync:
                params = jax.lax.pmean(params, axis)  # the one collective
            loss = jax.lax.pmean(loss_sum / k, axis)
            return (jax.tree.map(lambda a: a[None], params), loss)

        shmap = jax.shard_map(
            per_replica, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis)),
            out_specs=(P(self.axis), P()))
        return jax.jit(shmap)
