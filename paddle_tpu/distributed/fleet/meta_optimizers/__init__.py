"""Dygraph meta-optimizers with real TPU-native implementations.

Reference: python/paddle/distributed/fleet/meta_optimizers/ — the strategy-
driven program rewriters. On TPU the two that change *optimization
semantics* (not just communication scheduling) are implemented for real:

- ``DGCMomentumOptimizer`` — top-k gradient sparsification with error
  feedback (`dgc.py`).
- ``LocalSGD`` — k local steps per dp replica + compiled parameter
  averaging (`localsgd.py`).

The purely communication-scheduling ones (fuse_all_reduce, raw_program,
gradient_merge insertion) are XLA's job or live in
``meta_parallel.hybrid_parallel_optimizer``.
"""

from .dgc import DGCMomentumOptimizer
from .localsgd import LocalSGD

__all__ = ["DGCMomentumOptimizer", "LocalSGD"]
