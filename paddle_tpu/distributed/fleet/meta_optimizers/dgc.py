"""Deep Gradient Compression momentum optimizer.

Reference: python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py
(DGCMomentumOptimizer, sparsity rampup at :66-101) and the C++ op
paddle/fluid/operators/dgc_op.cc (momentum correction + error feedback:
u = m*u + g; v = v + u; communicate top-k of v; clear communicated slots).

TPU-native design
-----------------
The reference sparsifies so the NCCL allreduce moves only top-k values over
a bandwidth-limited interconnect. XLA collectives over ICI are dense — there
is no sparse-allreduce payload to shrink — so what matters for parity is the
*optimization algorithm*: momentum-corrected top-k selection with error
feedback (the residual of unsent gradient mass accumulates locally and is
never lost). That algorithm changes convergence behaviour and is implemented
here exactly; the communicated tensor stays dense (masked), which under SPMD
data parallelism is summed across the dp axis by the usual compiled
allreduce. Selection uses a quantile threshold on |v| (the paper's sampled
top-k estimator; the reference's dgc_op samples 1/1000 of the tensor for the
same reason).

The whole-model update is one jitted pytree function, matching the style of
``paddle_tpu.optimizer.optimizers``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ....optimizer.optimizer import Optimizer
from ....optimizer.optimizers import _f32

__all__ = ["DGCMomentumOptimizer"]


@functools.partial(jax.jit, donate_argnums=(0, 2, 3), static_argnums=(7,))
def _dgc_update(params, grads, us, vs, lr, mu, sparsity, use_nesterov, wds):
    """One post-rampup DGC step for every parameter.

    u: momentum-corrected accumulator, v: error-feedback residual
    (dgc_kernel.cu:154-173; the external dgc lib's ``k_select`` then zeroes
    the sent slots of both — momentum factor masking). The post-rampup
    parameter update is plain SGD on the communicated values, exactly as
    ``dgc_momentum_kernel_impl.h`` switches MomentumOp → SGDOp.
    """

    def upd(p, g, u, v, wd):
        gf = _f32(g) + wd * _f32(p)
        if use_nesterov:
            u_new = mu * (u + gf)                # u = m*(u + g)
            v_new = v + u_new + gf               # v = v + u + g
        else:
            u_new = mu * u + gf                  # momentum correction
            v_new = v + u_new                    # accumulate into residual
        av = jnp.abs(v_new).ravel()
        # threshold s.t. ~(1-sparsity) of entries are communicated; like the
        # dgc lib's k_select, estimate it from a sample instead of a full
        # sort once tensors get large (the lib samples ~1/1000)
        if av.size > 16384:
            av = av[:: av.size // 4096]
        thr = jnp.quantile(av, sparsity)
        mask = jnp.abs(v_new) >= thr
        comm = jnp.where(mask, v_new, 0.0)       # the "sent" gradient
        v_out = jnp.where(mask, 0.0, v_new)      # error feedback: unsent mass
        u_out = jnp.where(mask, 0.0, u_new)      # momentum factor masking
        new_p = (_f32(p) - lr * comm).astype(p.dtype)
        return new_p, u_out, v_out

    out = jax.tree.map(upd, params, grads, us, vs, wds)
    leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    return (jax.tree.map(lambda t: t[0], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[1], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[2], out, is_leaf=leaf))


class DGCMomentumOptimizer(Optimizer):
    """Momentum SGD with DGC top-k sparsification + error feedback.

    Args mirror the reference DGCMomentumOptimizer: before
    ``rampup_begin_step`` it is exact momentum SGD; across ``rampup_step``
    steps sparsity walks through ``sparsity`` (e.g. the paper's
    [0.75, 0.9375, 0.984375, 0.996, 0.999]); afterwards the final value
    holds.
    """

    _opt_name = "dgc_momentum"

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._rampup_begin_step = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))
        self._sparsity = list(sparsity)
        self._use_nesterov = use_nesterov

    def current_sparsity(self) -> float:
        """Sparsity in effect for the upcoming step (reference :66-101)."""
        step = self._global_step
        if step < self._rampup_begin_step:
            return 0.0
        i = (step - self._rampup_begin_step) * len(self._sparsity) \
            // self._rampup_step
        return self._sparsity[min(i, len(self._sparsity) - 1)]

    def _apply(self, params_grads):
        from ....optimizer.optimizers import _momentum_update

        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        wds = [self._weight_decay_value(p) for p, _ in params_grads]
        lr = jnp.float32(self.get_lr())
        sp = self.current_sparsity()
        if sp <= 0.0:
            # pre-rampup: exact momentum SGD (dgc_momentum_kernel_impl.h
            # runs MomentumOp while current_step < rampup_begin_step)
            vels = [self._acc("velocity", p) for p, _ in params_grads]
            new_p, new_v = _momentum_update(
                params, grads, vels, lr, jnp.float32(self._momentum),
                self._use_nesterov, wds)
            for (p, _), arr, v in zip(params_grads, new_p, new_v):
                p._rebind(arr)
                self._set_acc("velocity", p, v)
            return
        us = [self._acc("dgc_u", p) for p, _ in params_grads]
        vs = [self._acc("dgc_v", p) for p, _ in params_grads]
        new_p, new_u, new_v = _dgc_update(
            params, grads, us, vs, lr, jnp.float32(self._momentum),
            jnp.float32(sp), self._use_nesterov, wds)
        for (p, _), arr, u, v in zip(params_grads, new_p, new_u, new_v):
            p._rebind(arr)
            self._set_acc("dgc_u", p, u)
            self._set_acc("dgc_v", p, v)
