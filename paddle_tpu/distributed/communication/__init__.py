"""Collective communication API.

Reference: python/paddle/distributed/communication/ (all_reduce.py,
all_gather.py, reduce_scatter.py, all_to_all.py, broadcast.py, send/recv,
batch_isend_irecv.py, group.py, stream/).

TPU-native semantics (SURVEY.md §5.8): these are *traced* collectives — used
inside shard_map/pjit they lower to XLA ICI collectives (lax.psum /
all_gather / psum_scatter / all_to_all / ppermute). Eagerly, on the
single-controller model, every process sees the global array, so collectives
are value-preserving no-ops (world view already reduced/gathered); this keeps
metric-sync style call sites working. The `.wait()`-task object model is
preserved as immediate-complete tasks (XLA schedules overlap itself — the
reference's comm-stream tuning has no analog to expose).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..collective import Group, _get_default_group

__all__ = [
    "ReduceOp", "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "alltoall", "alltoall_single", "all_to_all", "broadcast",
    "broadcast_object_list", "scatter", "scatter_object_list", "gather",
    "send", "recv", "isend", "irecv", "barrier", "batch_isend_irecv", "P2POp",
    "stream", "wait",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class _Task:
    """Completed-at-creation task (ProcessGroup::Task analog)."""

    def __init__(self, value=None):
        self._value = value

    def wait(self):
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        pass


def _axis(group):
    g = group or _get_default_group()
    return getattr(g, "axis_name", None)


def _is_traced(t):
    return isinstance(t._data, jax.core.Tracer)


def _apply_inplace(tensor, arr):
    tensor._data = arr
    return tensor


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis(group)
    if _is_traced(tensor) and axis is not None:
        x = tensor._data
        if op in (ReduceOp.SUM, "sum"):
            out = jax.lax.psum(x, axis)
        elif op in (ReduceOp.MAX, "max"):
            out = jax.lax.pmax(x, axis)
        elif op in (ReduceOp.MIN, "min"):
            out = jax.lax.pmin(x, axis)
        elif op in (ReduceOp.AVG, "avg"):
            out = jax.lax.pmean(x, axis)
        else:
            out = jax.lax.psum(x, axis)
        return _Task(_apply_inplace(tensor, out))
    return _Task(tensor)  # eager single-controller: already the global value


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """paddle semantics: gather shards from all ranks into tensor_list."""
    grp = group or _get_default_group()
    ax = _axis(group)
    if _is_traced(tensor) and ax is not None:
        gathered = jax.lax.all_gather(tensor._data, ax)  # [n, ...]
        for i in range(grp.nranks):
            tensor_list.append(Tensor._wrap(gathered[i]))
        return _Task()
    for _ in range(grp.nranks):
        tensor_list.append(tensor)
    return _Task()


def all_gather_object(object_list, obj, group=None):
    grp = group or _get_default_group()
    for _ in range(grp.nranks):
        object_list.append(obj)
    return _Task()


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    grp = group or _get_default_group()
    ax = _axis(group)
    inputs = tensor_or_tensor_list
    if isinstance(inputs, (list, tuple)):
        stacked = jnp.concatenate([t._data for t in inputs], axis=0)
    else:
        stacked = inputs._data
    if isinstance(stacked, jax.core.Tracer) and ax is not None:
        out = jax.lax.psum_scatter(stacked, ax, scatter_dimension=0,
                                   tiled=True)
        return _Task(_apply_inplace(tensor, out))
    # eager: take this rank's slice of the (already-global) sum
    n = grp.nranks
    shard = stacked.shape[0] // n
    r = grp.rank
    return _Task(_apply_inplace(tensor, stacked[r * shard:(r + 1) * shard]))


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    grp = group or _get_default_group()
    ax = _axis(group)
    if in_tensor_list and _is_traced(in_tensor_list[0]) and ax is not None:
        stacked = jnp.stack([t._data for t in in_tensor_list])  # [n, ...]
        out = jax.lax.all_to_all(stacked, ax, split_axis=0, concat_axis=0,
                                 tiled=False)
        for i in range(grp.nranks):
            out_tensor_list.append(Tensor._wrap(out[i]))
        return _Task()
    out_tensor_list.extend(in_tensor_list)
    return _Task()


all_to_all = alltoall


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax = _axis(group)
    if _is_traced(in_tensor) and ax is not None:
        grp = group or _get_default_group()
        n = grp.nranks
        x = in_tensor._data.reshape(n, -1, *in_tensor._data.shape[1:])
        out = jax.lax.all_to_all(x, ax, split_axis=0, concat_axis=0,
                                 tiled=False)
        return _Task(_apply_inplace(out_tensor,
                                    out.reshape(in_tensor._data.shape)))
    return _Task(_apply_inplace(out_tensor, in_tensor._data))


def broadcast(tensor, src=0, group=None, sync_op=True):
    # single-controller: every process computes the same value — identity
    return _Task(tensor)


def broadcast_object_list(object_list, src=0, group=None):
    return _Task()


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    grp = group or _get_default_group()
    if tensor_list:
        return _Task(_apply_inplace(tensor, tensor_list[grp.rank]._data))
    return _Task(tensor)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    grp = group or _get_default_group()
    if in_object_list:
        out_object_list.append(in_object_list[grp.rank])
    return _Task()


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    grp = group or _get_default_group()
    if gather_list is not None:
        for _ in range(grp.nranks):
            gather_list.append(tensor)
    return _Task()


def send(tensor, dst=0, group=None, sync_op=True):
    """p2p send. Traced (inside shard_map with a group axis): lowers to a
    single-pair ppermute. Eager multi-process: there is no XLA p2p outside a
    compiled program — raise rather than silently return the local tensor
    (reference semantics: process_group_nccl.cc:228 moves real bytes)."""
    axis = _axis(group)
    if _is_traced(tensor) and axis is not None:
        out = jax.lax.ppermute(tensor._data, axis, [(0, dst)])
        return _Task(Tensor._wrap(out))
    if jax.process_count() > 1:
        raise NotImplementedError(
            "eager send() has no TPU point-to-point path in a multi-process "
            "run; use the pipeline engine (ppermute stage-scan) or a traced "
            "shard_map collective instead")
    return _Task(tensor)


def recv(tensor, src=0, group=None, sync_op=True):
    """p2p recv — see send(). Traced: ppermute from src; eager multi-process:
    raises instead of silently returning the caller's local tensor."""
    axis = _axis(group)
    if _is_traced(tensor) and axis is not None:
        me = 0  # static single-pair permute: src -> this logical position
        out = jax.lax.ppermute(tensor._data, axis, [(src, me)])
        return _Task(Tensor._wrap(out))
    if jax.process_count() > 1:
        raise NotImplementedError(
            "eager recv() has no TPU point-to-point path in a multi-process "
            "run; use the pipeline engine (ppermute stage-scan) or a traced "
            "shard_map collective instead")
    return _Task(tensor)


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    return [_Task(op.tensor) for op in p2p_op_list]


def barrier(group=None):
    # block host until all queued device work completes
    try:
        jax.effects_barrier()
    except Exception:
        pass
    return _Task()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and not _is_traced(tensor):
        try:
            tensor._data.block_until_ready()
        except Exception:
            pass
    return None


class _StreamNS:
    """paddle.distributed.stream.* variants (reference communication/stream/):
    same collectives; the sync/async distinction is XLA-scheduled."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    alltoall_single = staticmethod(alltoall_single)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)


stream = _StreamNS()
