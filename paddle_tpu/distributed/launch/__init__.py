"""Distributed launcher — ``python -m paddle_tpu.distributed.launch``.

Reference: python/paddle/distributed/launch/main.py + controllers
(launch/controllers/collective.py, master.py, watcher.py).

TPU-native redesign: the reference rendezvous (HTTP master / etcd) is
replaced by jax.distributed's coordination service — the launcher only
has to (1) compute the coordinator address, (2) start one worker process
per local device group with the PADDLE_* / MASTER_* env contract that
``paddle_tpu.distributed.init_parallel_env`` consumes, and (3) watch the
children (fault-tolerance = kill-all + relaunch, the reference's
FAULT_TOLERANCE elastic level; checkpoint-resume does the rest).
"""

from .controllers.collective import (  # noqa: F401
    CrashLoopError, RestartBudget,
)
from .main import main  # noqa: F401

# RestartBudget/CrashLoopError are exported here because supervision is
# no longer training-only: the serving fleet's ReplicaSupervisor
# (inference.serving.fleet) reuses the same leaky-bucket budget, backoff
# and crash-loop semantics — one supervision vocabulary for both sides.
__all__ = ["main", "RestartBudget", "CrashLoopError"]
