"""Launcher CLI (reference python/paddle/distributed/launch/main.py).

Usage::

    python -m paddle_tpu.distributed.launch \
        [--nnodes N] [--nproc_per_node M] [--master IP:PORT] \
        [--rank NODE_RANK] [--log_dir DIR] [--max_restart K] \
        script.py [script args...]

Single node (default): picks a free local port as the jax.distributed
coordinator and starts M workers. Multi-node: pass --master pointing at
node 0 and --rank for this node; every node runs the same command.
"""

from __future__ import annotations

import argparse
import sys

from .controllers.collective import (CollectiveController, CrashLoopError,
                                     _free_port)

__all__ = ["main", "parse_args"]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a distributed training job")
    p.add_argument("--master", default=None,
                   help="coordinator endpoint IP:PORT (node 0); "
                        "auto-selected for single-node jobs")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="workers per node (default: one per local device "
                        "group, i.e. 1 on a single-controller TPU host)")
    p.add_argument("--rank", "--node_rank", type=int, default=0,
                   dest="rank", help="this node's index in [0, nnodes)")
    p.add_argument("--log_dir", default=None,
                   help="write per-worker logs to DIR/workerlog.N")
    p.add_argument("--max_restart", type=int, default=0,
                   help="leaky-bucket restart budget: relaunch the whole "
                        "local group after a crash or hang up to K times "
                        "per FLAGS_restart_window_s rolling window, with "
                        "exponential backoff (FLAGS_restart_backoff_s). "
                        "Clean preemptions (a worker exiting 123 after a "
                        "graceful SIGTERM checkpoint) relaunch for free")
    p.add_argument("--devices", default=None,
                   help="comma list of local device ids to expose "
                        "(sets JAX_VISIBLE_DEVICES per worker)")
    p.add_argument("training_script", help="script (or binary) to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    args.master_auto = False
    if args.master is None:
        if args.nnodes > 1:
            raise SystemExit(
                "--master IP:PORT is required for multi-node jobs "
                "(point every node at node 0)")
        # auto-selected master: the controller picks a FRESH port each
        # restart round (master_auto) so rendezvous never collides with
        # the dead coordinator's TIME_WAIT socket
        args.master = f"127.0.0.1:{_free_port()}"
        args.master_auto = True
    elif ":" not in args.master or not args.master.rsplit(":", 1)[1].isdigit():
        raise SystemExit(
            f"--master must be IP:PORT, got {args.master!r}")
    ctrl = CollectiveController(args)
    try:
        return ctrl.run()
    except CrashLoopError as e:
        print(f"[launch] {e}", file=sys.stderr)
        return e.exit_code


if __name__ == "__main__":
    sys.exit(main())
