"""Collective job controller (reference launch/controllers/collective.py
+ watcher.py).

Starts nproc_per_node local workers with the PADDLE_*/MASTER_* env
contract, tails their exit codes, and on any nonzero exit kills the
whole local group and (optionally) relaunches it — the reference's
FAULT_TOLERANCE elastic level. Rendezvous is jax.distributed's
coordination service at MASTER_ADDR:MASTER_PORT, so there is no HTTP/
etcd master to run.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["CollectiveController"]


class CollectiveController:
    def __init__(self, args):
        self.args = args
        self.nproc = args.nproc_per_node or 1
        self.world_size = args.nnodes * self.nproc
        self.procs: list[subprocess.Popen] = []
        self._log_files = []

    # -- env contract ----------------------------------------------------
    def _worker_env(self, local_rank):
        env = dict(os.environ)
        addr, port = self.args.master.rsplit(":", 1)
        global_rank = self.args.rank * self.nproc + local_rank
        env.update({
            "PADDLE_MASTER": addr,
            "MASTER_ADDR": addr,
            "MASTER_PORT": port,
            "PADDLE_TRAINER_ID": str(global_rank),
            "PADDLE_TRAINERS_NUM": str(self.world_size),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_LOCAL_SIZE": str(self.nproc),
            "PADDLE_NNODES": str(self.args.nnodes),
            "PADDLE_NODE_RANK": str(self.args.rank),
        })
        if self.args.devices:
            devs = self.args.devices.split(",")
            dev = devs[local_rank % len(devs)]
            # per-platform visibility vars (jax reads the vendor ones)
            env["CUDA_VISIBLE_DEVICES"] = dev
            env["TPU_VISIBLE_DEVICES"] = dev
            env["JAX_VISIBLE_DEVICES"] = dev  # covers CPU backend
        return env

    def _cmd(self):
        script = self.args.training_script
        rest = list(self.args.training_script_args)
        if script.endswith(".py"):
            # bootstrap initializes jax.distributed BEFORE the user script
            # can touch the XLA backend (ordering is mandatory in jax)
            return [sys.executable, "-u", "-m",
                    "paddle_tpu.distributed.launch.bootstrap",
                    script] + rest
        return [script] + rest

    # -- lifecycle -------------------------------------------------------
    def _spawn_all(self):
        self._close_logs()  # previous restart round's handles
        self.procs = []
        for lr in range(self.nproc):
            out = None
            if self.args.log_dir:
                os.makedirs(self.args.log_dir, exist_ok=True)
                out = open(os.path.join(self.args.log_dir,
                                        f"workerlog.{lr}"), "ab")
                self._log_files.append(out)
            self.procs.append(subprocess.Popen(
                self._cmd(), env=self._worker_env(lr),
                stdout=out, stderr=(subprocess.STDOUT if out else None)))

    def _kill_all(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def _watch(self):
        """Block until the group finishes; return the first nonzero exit
        code, or 0 when every worker succeeded."""
        while True:
            codes = [p.poll() for p in self.procs]
            for rc in codes:
                if rc is not None and rc != 0:
                    self._kill_all()
                    return rc
            if all(rc == 0 for rc in codes):
                return 0
            time.sleep(0.2)

    def run(self):
        restarts = 0
        while True:
            self._spawn_all()
            rc = self._watch()
            if rc == 0:
                self._close_logs()
                return 0
            if restarts < self.args.max_restart:
                restarts += 1
                print(f"[launch] worker failed rc={rc}; restart "
                      f"{restarts}/{self.args.max_restart}",
                      file=sys.stderr)
                continue
            self._close_logs()
            return rc

    def _close_logs(self):
        for f in self._log_files:
            try:
                f.close()
            except Exception:
                pass
        self._log_files = []
