"""Collective job controller (reference launch/controllers/collective.py
+ watcher.py + the elastic manager's supervision loop).

Starts nproc_per_node local workers with the PADDLE_*/MASTER_* env
contract and supervises the group:

- **Crash**: any worker exiting with a real nonzero code kills the whole
  local group and relaunches it (the reference's FAULT_TOLERANCE elastic
  level; checkpoint auto-resume does the rest).
- **Hang**: workers heartbeat into ``PADDLE_HEARTBEAT_DIR`` (see
  ``launch.heartbeat``); when the *stalest* rank's heartbeat is older than
  ``FLAGS_worker_hang_timeout_s`` the group is SIGTERM→SIGKILL'd and
  restarted like a crash — a rank wedged in a collective can no longer
  hold the job forever.
- **Clean preemption**: a worker exiting with ``PREEMPT_EXIT_CODE`` (123,
  raised by ``FusedTrainStep.drive``'s SIGTERM handler after it committed
  a checkpoint) relaunches WITHOUT consuming restart budget — scheduler
  evictions are not crashes.
- **Crash-loop breaker**: the restart budget is a leaky bucket
  (``--max_restart`` crash restarts per ``FLAGS_restart_window_s`` rolling
  window, exponential backoff between relaunches) instead of a lifetime
  counter, so a week-old transient doesn't block recovery from today's
  node loss while a tight crash loop still exhausts quickly and raises a
  typed :class:`CrashLoopError`.

Each restart round of a single-node auto-selected master picks a fresh
coordinator port: the dead coordinator's socket can sit in TIME_WAIT and
make the next rendezvous fail spuriously. Rendezvous is jax.distributed's
coordination service at MASTER_ADDR:MASTER_PORT, so there is no HTTP/etcd
master to run.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

from ....core.flags import flag_value
from ....observability import metrics as _obs_metrics
from ..heartbeat import PREEMPT_EXIT_CODE, live_ranks as _hb_live

# rank-liveness gauge (ISSUE 10): how many of this node's workers look
# alive RIGHT NOW — process running, and (when the hang watchdog is
# armed) heartbeat mtime fresh enough. Updated every supervision tick;
# transitions are appended to <log_dir>/liveness.log so an external
# drill (scripts/chaos_train.py --scenarios kill) can assert the gauge
# dipped during a kill and recovered after the restart.
_G_LIVE_RANKS = _obs_metrics.gauge(
    "launch_live_ranks",
    "workers of this node currently alive (process running + heartbeat "
    "fresh when the hang watchdog is armed)")

__all__ = ["CollectiveController", "RestartBudget", "CrashLoopError",
           "HANG_EXIT_CODE", "PREEMPT_EXIT_CODE"]

# the controller's own code for "group killed for stale heartbeats" — no
# worker produced an exit code, so one is synthesized (124 = timeout(1))
HANG_EXIT_CODE = 124


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class CrashLoopError(RuntimeError):
    """The job kept crashing after its restart budget was exhausted
    (``--max_restart`` restarts within ``FLAGS_restart_window_s``).
    Carries the final worker exit code and total restarts performed, so
    the CLI can propagate the real failure instead of looping forever."""

    def __init__(self, msg, exit_code=1, restarts=0):
        super().__init__(msg)
        self.exit_code = exit_code
        self.restarts = restarts


class RestartBudget:
    """Leaky-bucket crash-loop breaker: at most ``max_restarts`` crash
    restarts within a rolling ``window_s`` window (old crashes age out),
    with exponential backoff between relaunches — delay doubles with each
    crash currently in the bucket, capped, so a tight crash loop slows
    down instead of hammering the scheduler. Clean preemptions go through
    :attr:`preemptions` and never touch the bucket. ``clock``/``sleep``
    are injectable for tests."""

    def __init__(self, max_restarts, window_s=None, backoff_base_s=None,
                 backoff_cap_s=30.0, clock=time.monotonic, sleep=time.sleep):
        self.max_restarts = int(max_restarts)
        self.window_s = float(
            flag_value("restart_window_s", 3600.0)
            if window_s is None else window_s)
        self.backoff_base_s = float(
            flag_value("restart_backoff_s", 1.0)
            if backoff_base_s is None else backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._clock = clock
        self._sleep = sleep
        self._events: list[float] = []
        self._preempt_events: list[float] = []
        self.total_restarts = 0
        self.preemptions = 0

    def _prune(self, now):
        if self.window_s > 0:
            self._events = [t for t in self._events
                            if now - t <= self.window_s]

    @property
    def used(self):
        """Crash restarts currently counted against the budget (in-window)."""
        self._prune(self._clock())
        return len(self._events)

    # clean preemptions are budget-free but not UNBOUNDED: a worker that
    # exits 123 over and over without the cluster ever letting it run is
    # indistinguishable from a crash loop, so past this many per rolling
    # window further preemptions are charged like crashes
    PREEMPT_CAP_PER_WINDOW = 16

    def try_acquire(self):
        """Record one crash restart; False when the bucket is full (the
        caller must stop relaunching)."""
        now = self._clock()
        self._prune(now)
        if len(self._events) >= self.max_restarts:
            return False
        self._events.append(now)
        self.total_restarts += 1
        return True

    def note_preemption(self):
        """Record one clean-preemption relaunch in its own leaky window —
        never the crash bucket, and with NO backoff (clean preemptions
        relaunch immediately, as the flag docs promise). False once the
        per-window cap is exceeded: a job exiting 123 over and over
        without progress is a crash loop wearing a polite exit code, and
        the caller should charge further preemptions as crashes (whose
        path brings the backoff)."""
        now = self._clock()
        if self.window_s > 0:
            self._preempt_events = [t for t in self._preempt_events
                                    if now - t <= self.window_s]
        if len(self._preempt_events) >= self.PREEMPT_CAP_PER_WINDOW:
            return False
        self._preempt_events.append(now)
        self.preemptions += 1
        return True

    def backoff(self):
        """Sleep the current backoff (exponential in in-window crash
        count, capped) and return the delay actually applied."""
        n = max(1, len(self._events))
        delay = min(self.backoff_cap_s,
                    self.backoff_base_s * (2 ** (n - 1)))
        if delay > 0:
            self._sleep(delay)
        return delay


class CollectiveController:
    def __init__(self, args):
        self.args = args
        self.nproc = args.nproc_per_node or 1
        self.world_size = args.nnodes * self.nproc
        self.procs: list[subprocess.Popen] = []
        self._log_files = []
        self._spawn_time = None
        # heartbeat rendezvous: under log_dir when given (inspectable after
        # the run), else a tmpdir — workers find it via PADDLE_HEARTBEAT_DIR
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            self._hb_dir = os.path.join(args.log_dir, "heartbeats")
        else:
            self._hb_dir = tempfile.mkdtemp(prefix="paddle_hb.")
        os.makedirs(self._hb_dir, exist_ok=True)
        self._last_live = None  # last launch_live_ranks value published

    # -- env contract ----------------------------------------------------
    def _worker_env(self, local_rank):
        env = dict(os.environ)
        addr, port = self.args.master.rsplit(":", 1)
        global_rank = self.args.rank * self.nproc + local_rank
        env.update({
            "PADDLE_MASTER": addr,
            "MASTER_ADDR": addr,
            "MASTER_PORT": port,
            "PADDLE_TRAINER_ID": str(global_rank),
            "PADDLE_TRAINERS_NUM": str(self.world_size),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_LOCAL_SIZE": str(self.nproc),
            "PADDLE_NNODES": str(self.args.nnodes),
            "PADDLE_NODE_RANK": str(self.args.rank),
            "PADDLE_HEARTBEAT_DIR": self._hb_dir,
        })
        if self.args.devices:
            devs = self.args.devices.split(",")
            dev = devs[local_rank % len(devs)]
            # per-platform visibility vars (jax reads the vendor ones)
            env["CUDA_VISIBLE_DEVICES"] = dev
            env["TPU_VISIBLE_DEVICES"] = dev
            env["JAX_VISIBLE_DEVICES"] = dev  # covers CPU backend
        return env

    def _cmd(self):
        script = self.args.training_script
        rest = list(self.args.training_script_args)
        if script.endswith(".py"):
            # bootstrap initializes jax.distributed BEFORE the user script
            # can touch the XLA backend (ordering is mandatory in jax)
            return [sys.executable, "-u", "-m",
                    "paddle_tpu.distributed.launch.bootstrap",
                    script] + rest
        return [script] + rest

    # -- lifecycle -------------------------------------------------------
    def _spawn_all(self):
        self._close_logs()  # previous restart round's handles
        # stale heartbeats from the previous round must not mask (or fake)
        # this round's liveness — every round starts from a clean slate
        for fn in os.listdir(self._hb_dir):
            try:
                os.remove(os.path.join(self._hb_dir, fn))
            except OSError:
                pass
        self.procs = []
        self._spawn_time = time.time()
        for lr in range(self.nproc):
            out = None
            if self.args.log_dir:
                os.makedirs(self.args.log_dir, exist_ok=True)
                out = open(os.path.join(self.args.log_dir,
                                        f"workerlog.{lr}"), "ab")
                self._log_files.append(out)
            self.procs.append(subprocess.Popen(
                self._cmd(), env=self._worker_env(lr),
                stdout=out, stderr=(subprocess.STDOUT if out else None)))

    def _kill_all(self):
        grace = float(flag_value("worker_term_grace_s", 10.0) or 10.0)
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + grace
        for p in self.procs:
            try:
                p.wait(timeout=max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def _watch(self):
        """Block until the group's round resolves. Returns 0 (all
        succeeded), PREEMPT_EXIT_CODE (>=1 worker preempted cleanly, none
        crashed), HANG_EXIT_CODE (heartbeats went stale — group killed),
        or the first real nonzero exit code (group killed)."""
        hang_timeout = float(flag_value("worker_hang_timeout_s", 0) or 0)
        grace = float(flag_value("worker_term_grace_s", 10.0) or 10.0)
        preempt_seen = None
        while True:
            codes = [p.poll() for p in self.procs]
            # publish liveness BEFORE the crash/hang judgments below, so
            # the tick that detects a dead rank records the dip first;
            # the hang check below derives from the same single
            # heartbeat-dir read instead of re-reading it
            running, live = self._note_liveness(codes, hang_timeout)
            crash = next((rc for rc in codes if rc is not None
                          and rc not in (0, PREEMPT_EXIT_CODE)), None)
            if crash is not None:
                self._kill_all()
                return crash
            if all(rc is not None for rc in codes):
                return (PREEMPT_EXIT_CODE
                        if any(rc == PREEMPT_EXIT_CODE for rc in codes)
                        else 0)
            if any(rc == PREEMPT_EXIT_CODE for rc in codes):
                # part of the group preempted cleanly; give the remaining
                # ranks one grace window to land their own preemption
                # checkpoint before reaping the round
                if preempt_seen is None:
                    preempt_seen = time.time()
                elif time.time() - preempt_seen > grace:
                    self._kill_all()
                    return PREEMPT_EXIT_CODE
            # judge only the still-running ranks: a finished or preempted
            # worker's aging heartbeat file must not condemn the live ones.
            # "some running rank missing from the fresh-heartbeat set" is
            # exactly the old stale()-over-the-stalest-rank judgment,
            # computed from this tick's single heartbeat-dir read
            if hang_timeout > 0 and running and live != running:
                print("[launch] worker heartbeats stale (no progress for "
                      f"{hang_timeout:g}s) — killing the hung group",
                      file=sys.stderr)
                self._kill_all()
                return HANG_EXIT_CODE
            time.sleep(0.2)

    def _note_liveness(self, codes, hang_timeout):
        """Set the ``launch_live_ranks`` gauge from this tick's evidence:
        a rank is live when its process is running and — with the hang
        watchdog armed — its heartbeat mtime is fresh (``hb.live_ranks``,
        spawn time as the not-yet-written grace anchor). Value changes are
        appended to ``<log_dir>/liveness.log`` (``<epoch-seconds> <n>``)
        so the chaos drill can assert the gauge flipped during a kill.
        Returns ``(running_ranks, live_ranks)`` so the caller's hang
        judgment reuses this tick's one heartbeat-dir read."""
        running = {str(self.args.rank * self.nproc + lr)
                   for lr, rc in enumerate(codes) if rc is None}
        live = set(running)
        if hang_timeout > 0 and live:
            live &= _hb_live(self._hb_dir, hang_timeout,
                             since=self._spawn_time, ranks=live)
        n = len(live)
        _G_LIVE_RANKS.set(n)
        if n != self._last_live:
            self._last_live = n
            if self.args.log_dir:
                try:
                    with open(os.path.join(self.args.log_dir,
                                           "liveness.log"), "a") as f:
                        f.write(f"{time.time():.3f} {n}\n")
                except OSError:
                    pass
        return running, live

    def _refresh_master(self):
        """Fresh coordinator port per restart round for auto-selected
        single-node masters: the dead round's port can linger in TIME_WAIT
        and collide with the new rendezvous."""
        if getattr(self.args, "master_auto", False) and self.args.nnodes == 1:
            addr = self.args.master.rsplit(":", 1)[0]
            self.args.master = f"{addr}:{_free_port()}"

    def run(self):
        budget = RestartBudget(self.args.max_restart)
        try:
            while True:
                self._spawn_all()
                rc = self._watch()
                if rc == 0:
                    return 0
                if rc == PREEMPT_EXIT_CODE and budget.note_preemption():
                    print(f"[launch] clean preemption (exit "
                          f"{PREEMPT_EXIT_CODE}); relaunching — restart "
                          f"budget untouched ({budget.used}/"
                          f"{budget.max_restarts} used)", file=sys.stderr)
                    self._refresh_master()
                    continue
                if rc == PREEMPT_EXIT_CODE:
                    reason = (f"preempt-looping (> "
                              f"{budget.PREEMPT_CAP_PER_WINDOW} clean "
                              f"preemptions per {budget.window_s:.0f}s "
                              "window) — charging further preemptions as "
                              "crashes")
                else:
                    reason = ("hang (stale heartbeats past "
                              "FLAGS_worker_hang_timeout_s)"
                              if rc == HANG_EXIT_CODE else f"rc={rc}")
                if budget.try_acquire():
                    self._refresh_master()
                    delay = budget.backoff()
                    print(f"[launch] worker failed ({reason}); restart "
                          f"{budget.used}/{budget.max_restarts} "
                          f"(backoff {delay:.1f}s)", file=sys.stderr)
                    continue
                raise CrashLoopError(
                    f"crash loop: worker failed ({reason}) with the "
                    f"restart budget exhausted ({budget.max_restarts} "
                    f"restarts per {budget.window_s:.0f}s window, "
                    f"{budget.total_restarts} performed); giving up",
                    exit_code=rc, restarts=budget.total_restarts)
        finally:
            self._close_logs()
            if not self.args.log_dir:  # tmpdir heartbeat rendezvous
                import shutil

                shutil.rmtree(self._hb_dir, ignore_errors=True)

    def _close_logs(self):
        for f in self._log_files:
            try:
                f.close()
            except Exception:
                pass
        self._log_files = []
