from .collective import CollectiveController  # noqa: F401

__all__ = ["CollectiveController"]
