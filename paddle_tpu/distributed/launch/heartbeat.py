"""Worker-side supervision contract: heartbeat files + graceful preemption.

The supervisor (``CollectiveController``) can see a worker *die* (exit
code) but not *wedge* — a rank parked in a collective whose peer is gone
looks exactly like one making slow progress. This module is the shared
contract that closes that gap:

- **Heartbeats.** The launcher exports ``PADDLE_HEARTBEAT_DIR`` to every
  worker; :func:`write` drops an atomic ``hb.<rank>`` JSON file (step +
  wall time) there. ``FusedTrainStep.drive`` calls it at every metric-fetch
  window boundary (and the launch bootstrap writes one at process start,
  so a long jax init never reads as a hang). The supervisor's
  :func:`stale` compares the *stalest* rank — training is lockstep, so one
  silent rank means the group is wedged even while the others still beat.
  Heartbeats are best-effort: a failed write (fault site ``hb.write``)
  returns ``False`` and training continues; losing supervision must never
  cause the failure it exists to detect.

- **Preemption.** A scheduler evicting a job sends SIGTERM.
  :func:`trap_preemption` installs a recording (not raising) handler so
  the training loop can finish its in-flight fetch window, write a
  committed checkpoint, and exit with :data:`PREEMPT_EXIT_CODE` — which
  the supervisor treats as *clean*: relaunch without consuming restart
  budget. Exit-code contract: ``0`` done, ``123`` preempted-with-
  checkpoint, anything else a crash.
"""

from __future__ import annotations

import contextlib
import json
import os
import signal
import threading
import time

__all__ = ["HEARTBEAT_DIR_ENV", "PREEMPT_EXIT_CODE", "heartbeat_dir",
           "write", "read_all", "stale", "live_ranks", "PreemptionState",
           "trap_preemption"]

HEARTBEAT_DIR_ENV = "PADDLE_HEARTBEAT_DIR"
# 123 is outside the shell/signal ranges workers produce by accident
# (128+N = killed by signal N; small codes = script errors)
PREEMPT_EXIT_CODE = 123


def heartbeat_dir():
    """The directory this process should heartbeat into, or ``None`` when
    running unsupervised (env unset — every write becomes a no-op)."""
    return os.environ.get(HEARTBEAT_DIR_ENV) or None


def write(step=None, dir=None, rank=None):
    """Atomically publish this worker's heartbeat (``hb.<rank>``: step,
    wall time, pid). Returns ``True`` on success, ``False`` when
    unsupervised (no dir) or the write failed — heartbeat failure is
    never allowed to crash training."""
    d = dir or heartbeat_dir()
    if not d:
        return False
    if rank is None:
        rank = os.environ.get("PADDLE_TRAINER_ID", "0")
    path = os.path.join(d, f"hb.{rank}")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        from ...utils import fault_injection

        fault_injection.fire("hb.write")
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"step": step, "time": time.time(),
                       "pid": os.getpid()}, f)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def read_all(dir):
    """``{rank: payload}`` for every parseable ``hb.*`` file under ``dir``
    (heartbeats are written atomically, so a partial file can only be a
    leftover tmp — those are skipped by name)."""
    out = {}
    try:
        entries = os.listdir(dir)
    except OSError:
        return out
    for fn in entries:
        if not fn.startswith("hb.") or ".tmp." in fn:
            continue
        try:
            with open(os.path.join(dir, fn)) as f:
                out[fn[3:]] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


def stale(dir, timeout_s, since=None, now=None, expected=None, ranks=None):
    """True when the group looks hung: the *stalest* rank's newest
    heartbeat is older than ``timeout_s``. Ranks that have not written yet
    are scored at ``since`` (the group's spawn time), so a worker that
    never starts beating is caught too — but a freshly spawned group is
    not declared hung before ``since + timeout_s``. ``expected`` is the
    number of workers the supervisor launched; without it only ranks that
    actually wrote are considered. ``ranks`` restricts the judgment to
    those rank ids — the supervisor passes its *still-running* workers, so
    the aging heartbeat file of a rank that already exited (done, or
    preempted cleanly) can never condemn the live ones as hung. Returns
    ``False`` when there is nothing to judge (no heartbeats and no
    baseline)."""
    if not timeout_s or float(timeout_s) <= 0:
        return False
    if now is None:
        now = time.time()
    beats = read_all(dir)
    if ranks is not None:
        allowed = {str(r) for r in ranks}
        beats = {r: v for r, v in beats.items() if r in allowed}
        expected = len(allowed)
    times = [float(v.get("time", 0.0)) for v in beats.values()]
    missing = 0 if expected is None else max(0, int(expected) - len(times))
    if missing and since is not None:
        times += [float(since)] * missing
    if not times:
        if since is None:
            return False
        times = [float(since)]
    return (now - min(times)) > float(timeout_s)


def live_ranks(dir, timeout_s, since=None, now=None, ranks=None):
    """Rank ids (strings) whose heartbeat looks alive: newest ``hb.<rank>``
    mtime within ``timeout_s`` of ``now``. A rank that has not written yet
    is scored at ``since`` (its spawn time) when given, so a freshly
    spawned worker counts as live until ``since + timeout_s`` — the same
    grace :func:`stale` applies. ``ranks`` names the candidate set (the
    supervisor passes its workers); without it only ranks that wrote are
    considered. Feeds the supervisor's ``launch_live_ranks`` gauge
    (``paddle.observability.metrics``)."""
    if now is None:
        now = time.time()
    beats = read_all(dir)
    candidates = ({str(r) for r in ranks} if ranks is not None
                  else set(beats))
    out = set()
    for r in candidates:
        t = beats.get(r, {}).get("time")
        if t is None:
            t = since
        if t is None:
            continue
        if not timeout_s or float(timeout_s) <= 0 \
                or (now - float(t)) <= float(timeout_s):
            out.add(r)
    return out


class PreemptionState:
    """Cross-references the signal a :func:`trap_preemption` scope
    absorbed. ``triggered`` flips once and stays set; ``signum`` records
    which signal arrived."""

    __slots__ = ("triggered", "signum")

    def __init__(self):
        self.triggered = False
        self.signum = None


@contextlib.contextmanager
def trap_preemption(signals=(signal.SIGTERM,), enable=True):
    """Record (instead of dying on) preemption signals for the duration of
    the block; previous handlers are restored on exit. Yields a
    :class:`PreemptionState` the loop polls at its window boundaries.
    Off the main thread (or with ``enable=False``) the state is yielded
    inert — signal handlers can only be installed from the main thread."""
    state = PreemptionState()
    installed = {}
    if enable and threading.current_thread() is threading.main_thread():
        def _handler(signum, frame):
            state.triggered = True
            state.signum = signum

        try:
            for s in signals:
                installed[s] = signal.signal(s, _handler)
        except (ValueError, OSError):
            for s, h in installed.items():
                signal.signal(s, h)
            installed = {}
    try:
        yield state
    finally:
        for s, h in installed.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
