"""Worker bootstrap: runs inside every launched worker BEFORE the user
script, so jax.distributed is initialized before any code can touch the
XLA backend (jax requires initialize() first). The reference trainers do
the equivalent inside init_parallel_env from the launcher's env; here the
ordering constraint is hard, so the launcher owns it."""

from __future__ import annotations

import os
import runpy
import sys


def main():
    # first heartbeat BEFORE the heavy imports/rendezvous: the launcher's
    # hang watchdog must not mistake a long jax init for a wedged worker
    from . import heartbeat

    heartbeat.write(step=None)
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    # PADDLE_SKIP_DIST_INIT: launcher-supervised workers that shard only
    # DATA (independent replicas over a sharded stream — no cross-rank
    # collectives, per-rank checkpoints) opt out of the coordination
    # service: they must not share commit barriers that would couple
    # their otherwise-independent checkpoint directories. Supervision
    # (heartbeats, watchdog, restart budget) is unaffected.
    if nprocs > 1 and not os.environ.get("PADDLE_SKIP_DIST_INIT"):
        import jax

        # sitecustomize-style PJRT plugins can override JAX_PLATFORMS;
        # re-assert the env var through the config API
        if os.environ.get("JAX_PLATFORMS"):
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        coord = (os.environ.get("PADDLE_MASTER")
                 or os.environ.get("MASTER_ADDR", "127.0.0.1"))
        port = os.environ.get("MASTER_PORT", "8471")
        jax.distributed.initialize(
            coordinator_address=f"{coord}:{port}",
            num_processes=nprocs,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
        )
    script = sys.argv[1]
    sys.argv = sys.argv[1:]
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
