"""paddle.distributed.to_static / DistModel / Strategy.

Reference: python/paddle/distributed/auto_parallel/api.py — Strategy :781,
DistModel :969, to_static :1338. The reference converts a dygraph layer
with shard_tensor-annotated parameters into a static distributed program;
here the same contract rides the auto-parallel ``Engine`` (GSPMD compiles
the whole step, shardings come from the placements already attached to the
parameters)."""

from __future__ import annotations

from .auto_parallel.engine import Engine
from .auto_parallel.engine import Strategy as _EngineStrategy

__all__ = ["Strategy", "DistModel", "to_static"]


class Strategy(_EngineStrategy):
    """Parallel/optimization config (reference api.py:781) — same dict
    surface as the Engine strategy."""


class DistModel:
    """Train/eval/predict facade over the compiled distributed step
    (reference api.py:969: __call__ dispatches on the current mode)."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        self.network = layer
        self._loader = loader
        self._engine = Engine(model=layer, loss=loss, optimizer=optimizer,
                              metrics=metrics, strategy=strategy)
        self._mode = "train" if optimizer is not None and loss is not None \
            else ("eval" if loss is not None else "predict")

    def train(self):
        self._mode = "train"
        if hasattr(self.network, "train"):
            self.network.train()

    def eval(self):
        self._mode = "eval"
        if hasattr(self.network, "eval"):
            self.network.eval()

    def predict(self):
        self._mode = "predict"
        if hasattr(self.network, "eval"):
            self.network.eval()

    @property
    def mode(self):
        return self._mode

    def __call__(self, *args):
        """One step in the current mode: train -> loss (with parameter
        update), eval -> loss, predict -> outputs (reference api.py
        DistModel.__call__)."""
        eng = self._engine
        batch = eng._shard_batch(args)
        if self._mode == "train":
            return eng._build_step()(*batch)
        if self._mode == "eval":
            if eng._loss is None:
                raise ValueError(
                    "DistModel was built without a loss; eval mode needs "
                    "one (pass loss= to dist.to_static, or use predict())")
            *ins, label = batch
            return eng._loss(self.network(*ins), label)
        return self.network(*batch)

    def state_dict(self, mode="all"):
        return self.network.state_dict()

    def set_state_dict(self, state_dict):
        return self.network.set_state_dict(state_dict)

    def dist_main_program(self, mode=None):  # static-graph introspection
        return None

    def dist_startup_program(self, mode=None):
        return None


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """Returns (DistModel, loader) like the reference (api.py:1338);
    the loader passes through — batches are dp-sharded per step by the
    engine."""
    dm = DistModel(layer, loader=loader, loss=loss, optimizer=optimizer,
                   strategy=strategy)
    return dm, loader
