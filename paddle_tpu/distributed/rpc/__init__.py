"""paddle.distributed.rpc — simple RPC between named workers.

Reference: python/paddle/distributed/rpc/rpc.py (init_rpc :73, rpc_sync
:143, rpc_async :183, shutdown :278, get_worker_info :309,
get_all_worker_infos :339) over a C++ TensorPipe agent
(paddle/fluid/distributed/rpc/rpc_agent.h).

TPU-native redesign: RPC is host-side control plane (parameter-server-style
coordination, metrics, orchestration) — data-plane tensors ride XLA
collectives, never RPC. So the agent is a small threaded TCP server with
pickled (fn, args) payloads; worker discovery goes through the same
shared-filesystem FileStore the elastic launcher uses (rendezvous derived
from ``master_endpoint``). Each request gets a fresh connection; results or
remote exceptions come back pickled, and ``rpc_async`` returns a
concurrent.futures.Future.
"""

from __future__ import annotations

import concurrent.futures
import os
import pickle
import socket
import socketserver
import struct
import tempfile
import threading
import time

from ..fleet.elastic import FileStore


class _KVStore(FileStore):
    """Key->value JSON store on the FileStore's locked read-modify-write.
    One rendezvous file per master_endpoint; reuse an endpoint only for one
    gang at a time (the reference's TCP store has the same contract)."""

    def set(self, k, v):
        with self._locked():
            d = self._read()
            d[k] = v
            self._write(d)

    def get(self, k):
        return self._read().get(k)

    def items(self):
        return list(self._read().items())

    def delete(self, k):
        self.deregister(k)

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]

_DEFAULT_TIMEOUT = 30.0


class WorkerInfo:
    """reference rpc.py WorkerInfo(name, rank, ip, port)."""

    def __init__(self, name, rank, ip, port):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name}, rank={self.rank}, "
                f"ip={self.ip}, port={self.port})")


class _State:
    server = None
    server_thread = None
    self_info = None
    workers = {}  # name -> WorkerInfo
    store = None


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


def _send_msg(sock, payload: bytes):
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock):
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            fn, args, kwargs = pickle.loads(_recv_msg(self.request))
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # remote exception travels back
                result = (False, e)
            _send_msg(self.request, pickle.dumps(result, protocol=4))
        except (ConnectionError, EOFError):
            pass


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def _store_path(master_endpoint):
    key = (master_endpoint or "default").replace(":", "_").replace("/", "_")
    return os.path.join(tempfile.gettempdir(), f"paddle_tpu_rpc_{key}.json")


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """reference rpc.py:73 — start this worker's agent and wait for the
    whole gang to register."""
    if _State.server is not None:
        raise RuntimeError("init_rpc already called in this process")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0) if rank is None
               else rank)
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)
                     if world_size is None else world_size)
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", "127.0.0.1:0")

    server = _Server(("127.0.0.1", 0), _Handler)
    ip, port = server.server_address
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name=f"rpc-agent-{name}")
    t.start()
    _State.server, _State.server_thread = server, t
    _State.self_info = WorkerInfo(name, rank, ip, port)

    store = _KVStore(_store_path(master_endpoint))
    store.set(f"worker_{name}", {"name": name, "rank": rank, "ip": ip,
                                 "port": port})
    _State.store = store

    deadline = time.time() + _DEFAULT_TIMEOUT
    observed = 0
    while time.time() < deadline:
        infos = {k: v for k, v in store.items()
                 if k.startswith("worker_")}
        # a crashed previous gang leaves stale entries behind (shutdown
        # never ran): probe each endpoint and evict the dead ones instead
        # of accepting them into the gang
        live = {}
        for k, v in infos.items():
            if v["name"] == name:
                live[k] = v
                continue
            try:
                socket.create_connection((v["ip"], v["port"]),
                                         timeout=0.5).close()
                live[k] = v
            except OSError:
                store.delete(k)
        observed = len(live)
        if observed >= world_size:
            _State.workers = {
                v["name"]: WorkerInfo(v["name"], v["rank"], v["ip"],
                                      v["port"])
                for v in live.values()}
            return
        time.sleep(0.05)
    raise TimeoutError(
        f"init_rpc: only {observed}/{world_size} workers "
        "registered before timeout")


def _invoke(to, fn, args, kwargs, timeout):
    info = get_worker_info(to)
    with socket.create_connection((info.ip, info.port),
                                  timeout=timeout) as sock:
        _send_msg(sock, pickle.dumps((fn, args or (), kwargs or {}),
                                     protocol=4))
        sock.settimeout(timeout)
        ok, result = pickle.loads(_recv_msg(sock))
    if not ok:
        raise result
    return result


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_TIMEOUT):
    """reference rpc.py:143 — blocking remote call; remote exceptions
    re-raise locally."""
    return _invoke(to, fn, args, kwargs, timeout)


_pool = concurrent.futures.ThreadPoolExecutor(max_workers=8,
                                              thread_name_prefix="rpc")


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_TIMEOUT):
    """reference rpc.py:183 — returns a Future with .wait()/.result()."""
    fut = _pool.submit(_invoke, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # reference API calls it wait()
    return fut


def get_worker_info(name):
    """reference rpc.py:309."""
    if name not in _State.workers and _State.store is not None:
        v = _State.store.get(f"worker_{name}")
        if v:
            _State.workers[name] = WorkerInfo(v["name"], v["rank"],
                                              v["ip"], v["port"])
    if name not in _State.workers:
        raise ValueError(f"unknown rpc worker {name!r}")
    return _State.workers[name]


def get_all_worker_infos():
    """reference rpc.py:339."""
    return sorted(_State.workers.values(), key=lambda w: w.rank)


def get_current_worker_info():
    return _State.self_info


def shutdown():
    """reference rpc.py:278 — stop the agent and deregister."""
    if _State.server is None:
        return
    if _State.store is not None and _State.self_info is not None:
        try:
            _State.store.delete(f"worker_{_State.self_info.name}")
        except Exception:
            pass
    _State.server.shutdown()
    _State.server.server_close()
    _State.server = None
    _State.server_thread = None
    _State.self_info = None
    _State.workers = {}
    _State.store = None
