"""paddle.distributed.io — persistable save/load for distributed training.

Reference: python/paddle/distributed/io.py (save_persistables :~180,
load_persistables, is_persistable). The reference walks a static Program's
persistable vars; here persistables are the model's parameters + buffers,
and the sharded-checkpoint path (distributed.checkpoint) is the real
multi-host format — these entry points keep the single-artifact UX.
"""

from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables", "is_persistable"]


def is_persistable(var):
    """Parameters and registered buffers persist; activations don't."""
    from ..core.tensor import Parameter, Tensor

    if isinstance(var, Parameter):
        return True
    return isinstance(var, Tensor) and getattr(var, "persistable", False)


def save_persistables(executor_or_layer, dirname, main_program=None,
                      filename=None):
    """Save a layer's persistable state under ``dirname`` (reference
    io.py save_persistables; executor arg accepted for signature parity —
    eager mode has no scope to walk, the layer is the source of truth)."""
    from ..framework.io import save

    layer = main_program if main_program is not None else executor_or_layer
    if not hasattr(layer, "state_dict"):
        raise TypeError("save_persistables needs a Layer (or pass it as "
                        "main_program for reference-signature parity)")
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "__persistables__.pdparams")
    save(layer.state_dict(), path)
    return path


def load_persistables(executor_or_layer, dirname, main_program=None,
                      filename=None):
    from ..framework.io import load

    layer = main_program if main_program is not None else executor_or_layer
    path = os.path.join(dirname, filename or "__persistables__.pdparams")
    state = load(path)
    layer.set_state_dict(state)
    return layer
