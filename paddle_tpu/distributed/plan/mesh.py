"""Mesh declaration for sharding plans.

One place that turns ``{"dp": 2, "tp": 2}`` into a ``jax.sharding.Mesh``
over ``jax.devices()`` with the repo's canonical axis vocabulary. The mesh
is CPU-testable anywhere: export
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (plus
``JAX_PLATFORMS=cpu``) before jax initializes and ``jax.devices()`` serves
N virtual host devices — the same trick the tier-1 conftest and the graft
dryrun use, so every plan in this repo compiles and runs under pytest.
"""

from __future__ import annotations

import jax

__all__ = ["AXES", "make_mesh", "mesh_axes"]

# canonical axis order: pipeline outermost (manual stage scan), then the
# batch-ish axes, then the within-layer axes. A plan mesh uses a subset, in
# this order, so two plans over the same degrees fingerprint identically.
AXES = ("pp", "dp", "fsdp", "tp", "sep", "ep")


def make_mesh(axes, devices=None):
    """Build a named device mesh from ``{"axis": degree}``.

    ``axes`` may be a dict or a sequence of ``(name, degree)`` pairs. Axis
    names outside :data:`AXES` are allowed (custom meshes) but dict inputs
    are reordered to the canonical order; pair-sequences keep caller order.
    Degree-1 axes are kept — they cost nothing and keep specs stable when a
    degree is turned down to 1.
    """
    if isinstance(axes, dict):
        known = [a for a in AXES if a in axes]
        extra = [a for a in axes if a not in AXES]
        names = tuple(known + extra)
        sizes = tuple(int(axes[a]) for a in names)
    else:
        pairs = list(axes)
        names = tuple(str(n) for n, _ in pairs)
        sizes = tuple(int(s) for _, s in pairs)
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate mesh axis names: {names}")
    if any(s < 1 for s in sizes):
        raise ValueError(f"mesh axis degrees must be >= 1: "
                         f"{dict(zip(names, sizes))}")
    need = 1
    for s in sizes:
        need *= s
    if devices is None:
        devices = jax.devices()
    if len(devices) < need:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {need} devices, have "
            f"{len(devices)}; on CPU export XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} (before jax "
            "initializes) to get a virtual mesh")
    return jax.make_mesh(
        sizes, names,
        axis_types=(jax.sharding.AxisType.Auto,) * len(names),
        devices=tuple(devices[:need]))


def mesh_axes(mesh):
    """``{axis: degree}`` of a mesh, in mesh order."""
    return {name: int(size) for name, size in
            zip(mesh.axis_names, mesh.devices.shape)}
