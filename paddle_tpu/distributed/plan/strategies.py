"""The strategy table: named, parameterized plan builders.

Each strategy is a registered function ``(plan, **kwargs) -> None`` that
appends rules / sets fields on a :class:`~.plan.Plan`. Adding a parallel
strategy to this repo means adding a ROW HERE (plus a test —
``scripts/check_plan_coverage.py`` fails tier-1 when a registered strategy
has no exercising test), not a new compile path: every strategy lowers
through ``compile_step_with_plan``.

Registered today:

========  ==================================================================
``dp``    batch dim 0 of every data input over the ``dp`` axis
``zero1`` optimizer moments sharded dim-0 over an axis (stage-1 layout);
          params replicated — GSPMD gathers nothing extra
``zero2`` zero1 + gradient reduce-scatter layout (same moment sharding; the
          grads of a dim-0-sharded update land sharded by propagation)
``zero3`` zero2 + params themselves sharded dim-0 over the axis
          (gather-on-use compiled by GSPMD)
``tp``    Megatron tensor parallel: column/row rules for the llama family
          (q/k/v/gate/up column, o/down row, vocab-parallel embedding,
          column-parallel lm_head) or caller-provided rules
``sep``   sequence parallelism: data seq dim over ``sep`` and the
          attention collective implementation (``ring`` ppermute rotation
          or ``ulysses`` all_to_all head/seq re-shard)
``ep``    MoE expert parallelism: expert-stacked FFN weights dim-0 over
          ``ep``
``pp``    pipeline stages (consumed by the stage-scan engine)
========  ==================================================================
"""

from __future__ import annotations

from .mesh import mesh_axes
from .plan import Plan, PlanError


def _check_axis(plan, axis, strategy):
    """Fail at declaration (typed PlanError, like add_param_rule /
    shard_data_dim) instead of a raw KeyError deep in the first adopter's
    moment placement."""
    if axis not in mesh_axes(plan.mesh):
        raise PlanError(
            f"strategy {strategy!r}: axis {axis!r} not on mesh "
            f"{tuple(mesh_axes(plan.mesh))}")

__all__ = ["STRATEGIES", "register_strategy", "apply"]

STRATEGIES: dict = {}


def register_strategy(name):
    def deco(fn):
        STRATEGIES[name] = fn
        return fn
    return deco


def apply(plan: Plan, name: str, **kwargs):
    try:
        builder = STRATEGIES[name]
    except KeyError:
        raise PlanError(
            f"unknown strategy {name!r}; registered: "
            f"{sorted(STRATEGIES)}") from None
    builder(plan, **kwargs)
    plan._record(name, **kwargs)
    return plan


# llama-family Megatron TP rules ([in, out] Linear weight convention —
# the same table LlamaForCausalLM.tp_partition_spec publishes)
_LLAMA_TP_RULES = (
    ("*embed_tokens*", {0: "tp"}),          # vocab-parallel embedding
    ("*lm_head*", {1: "tp"}),               # column-parallel head
    ("*q_proj*", {1: "tp"}),
    ("*k_proj*", {1: "tp"}),
    ("*v_proj*", {1: "tp"}),
    ("*gate_proj*", {1: "tp"}),
    ("*up_proj*", {1: "tp"}),
    ("*o_proj*", {0: "tp"}),
    ("*down_proj*", {0: "tp"}),
)

_EP_RULES = (
    ("*gate_w*", {0: "ep"}),                # expert-stacked [E, ...] FFN
    ("*up_w*", {0: "ep"}),
    ("*down_w*", {0: "ep"}),
)


@register_strategy("dp")
def _dp(plan, axis="dp"):
    plan.shard_data_dim(0, axis)


@register_strategy("zero1")
def _zero1(plan, axis="dp"):
    _check_axis(plan, axis, "zero1")
    plan.moment_axis = axis


@register_strategy("zero2")
def _zero2(plan, axis="dp"):
    # the grad of a dim-0-sharded moment update lands sharded by GSPMD
    # propagation (reduce-scatter, or its unfused all-reduce+slice form on
    # XLA:CPU) — no extra rule beyond the stage-1 moment layout
    _check_axis(plan, axis, "zero2")
    plan.moment_axis = axis


@register_strategy("zero3")
def _zero3(plan, axis="dp"):
    _check_axis(plan, axis, "zero3")
    plan.moment_axis = axis
    plan.param_fallback_axis = axis


@register_strategy("tp")
def _tp(plan, rules=None):
    for pattern, spec in (rules or _LLAMA_TP_RULES):
        plan.add_param_rule(pattern, spec)


@register_strategy("sep")
def _sep(plan, impl="ring", axis="sep", data_dim=1):
    if impl not in ("ring", "ulysses"):
        raise PlanError(f"sep impl must be 'ring' or 'ulysses', got "
                        f"{impl!r}")
    plan.sep_impl = impl
    plan.sep_axis = axis
    plan.shard_data_dim(data_dim, axis)


@register_strategy("ep")
def _ep(plan, rules=None):
    for pattern, spec in (rules or _EP_RULES):
        plan.add_param_rule(pattern, spec)


@register_strategy("pp")
def _pp(plan, stages=2):
    if int(stages) < 1:
        raise PlanError(f"pp stages must be >= 1, got {stages}")
    plan.pp_stages = int(stages)
