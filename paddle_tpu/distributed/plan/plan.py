"""The sharding ``Plan``: parallelism as a declarative datum.

A Plan is (mesh, param rules, activation rules, strategy entries):

- **param rules** — ordered ``(name-pattern, dim spec)`` pairs, matched
  with :mod:`fnmatch` against the structured parameter names PR 2
  introduced (``llama.layers.0.self_attn.q_proj.weight``). First match
  wins; a dim whose size the axis does not divide is silently replicated
  for that param (the same degrade rule the graft dryrun used), so one
  rule table serves every model size.
- **activation rules** — a dim→axis map for data batches (``{0: "dp",
  1: "sep"}``), applied by the adopters when staging inputs.
- **strategy entries** — the named, parameterized builders registered in
  :mod:`.strategies` (``dp``/``zero1..3``/``tp``/``sep``/``ep``/``pp``).
  A strategy is a table row that appends rules and sets plan fields; it is
  NOT a code path: every strategy lowers through the same
  :func:`paddle_tpu.distributed.plan.compile_step_with_plan`.

The fingerprint (mesh shape + rule digest) is what
``CheckpointManager`` records per step so a restore onto an incompatible
mesh fails with a typed error instead of mis-sharding silently.
"""

from __future__ import annotations

import fnmatch
import hashlib

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import make_mesh, mesh_axes

__all__ = ["Plan", "PlanError"]


class PlanError(ValueError):
    """A plan declaration that cannot be realized (unknown axis, unknown
    strategy, malformed rule)."""


def _as_dims(spec):
    """Normalize a rule spec to a tuple of per-dim entries (axis name,
    tuple of axis names, or None). Accepts PartitionSpec, tuple/list, or a
    dict {dim: axis} (the ``tp_partition_spec`` shape)."""
    if spec is None:
        return ()
    if isinstance(spec, P):
        return tuple(spec)
    if isinstance(spec, dict):
        if not spec:
            return ()
        hi = max(spec)
        return tuple(spec.get(d) for d in range(hi + 1))
    return tuple(spec)


class Plan:
    """Declarative parallelism over one mesh. Build directly or through
    :meth:`Plan.build`'s strategy table::

        plan = Plan.build({"dp": 2, "tp": 2, "ep": 2},
                          ["dp", "tp", "ep", ("zero1", {"axis": "dp"})])

    and hand it to ``FusedTrainStep(plan=...)``, hapi
    ``Model.prepare(plan=...)`` or ``LLMEngine(plan=...)`` — all three
    compile through ``compile_step_with_plan``.
    """

    def __init__(self, mesh):
        self.mesh = mesh
        self.param_rules: list[tuple[str, tuple]] = []
        self.data_dims: dict[int, str] = {}
        # moment (optimizer-state) layout override: ("axis", dim) — the
        # zeroN strategies shard moments along dim 0 of every param whose
        # dim 0 the axis divides (DygraphShardingOptimizer stage-1 layout)
        self.moment_axis: str | None = None
        # parameter fallback sharding axis (zero3): applied after the rule
        # table for params no rule matched
        self.param_fallback_axis: str | None = None
        self.sep_impl: str | None = None       # "ring" | "ulysses"
        self.sep_axis: str = "sep"
        self.pp_stages: int | None = None
        self.strategies: list[tuple[str, dict]] = []

    # -- construction ---------------------------------------------------
    @classmethod
    def build(cls, axes, strategies=(), devices=None):
        """Mesh from ``axes`` (dict / pair list / an existing Mesh), then
        apply ``strategies``: each entry a registered name or ``(name,
        kwargs)``."""
        from .strategies import apply as _apply

        mesh = axes if isinstance(axes, jax.sharding.Mesh) \
            else make_mesh(axes, devices=devices)
        plan = cls(mesh)
        for entry in strategies:
            if isinstance(entry, str):
                name, kwargs = entry, {}
            else:
                name, kwargs = entry
            _apply(plan, name, **(kwargs or {}))
        return plan

    def add_param_rule(self, pattern, spec):
        """Append ``(fnmatch pattern, per-dim spec)``; earlier rules win."""
        dims = _as_dims(spec)
        axes = mesh_axes(self.mesh)
        for d in dims:
            for ax in (d if isinstance(d, (tuple, list)) else (d,)):
                if ax is not None and ax not in axes:
                    raise PlanError(
                        f"rule {pattern!r}: axis {ax!r} not on mesh "
                        f"{tuple(axes)}")
        self.param_rules.append((str(pattern), dims))
        return self

    def shard_data_dim(self, dim, axis):
        if axis not in mesh_axes(self.mesh):
            raise PlanError(f"data dim {dim}: axis {axis!r} not on mesh")
        self.data_dims[int(dim)] = axis
        return self

    def _record(self, name, **kwargs):
        self.strategies.append((name, dict(kwargs)))

    # -- resolution -----------------------------------------------------
    def _axis_size(self, entry):
        axes = mesh_axes(self.mesh)
        if isinstance(entry, (tuple, list)):
            n = 1
            for ax in entry:
                n *= axes[ax]
            return n
        return axes[entry]

    def spec_for(self, name, shape):
        """PartitionSpec for a parameter: first matching rule, with
        non-divisible (or degree-1) dims degraded to replication, then the
        zero3 fallback axis on dim 0."""
        dims = None
        for pattern, spec in self.param_rules:
            if fnmatch.fnmatchcase(name, pattern):
                dims = spec
                break
        out = [None] * len(shape)
        if dims is not None:
            for i, ax in enumerate(dims[:len(shape)]):
                if ax is None:
                    continue
                size = self._axis_size(ax)
                if size > 1 and shape[i] % size == 0:
                    out[i] = tuple(ax) if isinstance(ax, list) else ax
        if (dims is None and self.param_fallback_axis is not None
                and len(shape)):
            size = self._axis_size(self.param_fallback_axis)
            if size > 1 and shape[0] % size == 0:
                out[0] = self.param_fallback_axis
        return P(*out)

    def sharding_for(self, name, shape):
        return NamedSharding(self.mesh, self.spec_for(name, shape))

    def rule_dims(self, name):
        """Raw matched rule dims for ``name`` (``None`` when no rule
        matches) — the shape-free per-dim tuple the pp stage-scan's
        ``block_param_spec`` callback consumes (it applies its own
        divisibility handling on the stacked block shapes)."""
        for pattern, spec in self.param_rules:
            if fnmatch.fnmatchcase(name, pattern):
                return tuple(spec) or None
        return None

    def moment_spec_for(self, name, shape):
        """Optimizer-moment layout: the zeroN axis on dim 0 when it
        divides, else the param's own spec (moments follow their param)."""
        if self.moment_axis is not None and len(shape):
            size = self._axis_size(self.moment_axis)
            if size > 1 and shape[0] % size == 0:
                return P(self.moment_axis, *([None] * (len(shape) - 1)))
        return self.spec_for(name, shape)

    def moment_sharding_for(self, name, shape):
        return NamedSharding(self.mesh, self.moment_spec_for(name, shape))

    def data_spec(self, ndim, shape=None):
        """PartitionSpec for a data input of rank ``ndim`` from the
        activation rules (dims beyond the map replicate). With ``shape``,
        dims the axis does not divide degrade to replication — the same
        rule the param table uses, so odd-sized label/aux inputs ride
        along instead of erroring."""
        out = [None] * ndim
        for dim, axis in self.data_dims.items():
            if not (0 <= dim < ndim and self._axis_size(axis) > 1):
                continue
            if shape is not None and shape[dim] % self._axis_size(axis):
                continue
            out[dim] = axis
        return P(*out)

    def data_sharding(self, ndim, shape=None):
        return NamedSharding(self.mesh, self.data_spec(ndim, shape))

    def place_data(self, arr):
        """Commit a host/device array to its activation sharding (rank-0
        scalars pass through)."""
        if not getattr(arr, "ndim", 0):
            return arr
        return jax.device_put(arr, self.data_sharding(arr.ndim, arr.shape))

    def place_params(self, named_arrays, moments=False):
        """device_put a ``{name: array}`` tree onto the plan's layout."""
        pick = self.moment_sharding_for if moments else self.sharding_for
        return {n: jax.device_put(a, pick(n, a.shape))
                for n, a in named_arrays.items()}

    def apply_to_model(self, model):
        """Adopt the plan on a live Layer: commit every parameter Tensor's
        array to its plan sharding IN PLACE (autograd identity preserved),
        and wire the sequence-parallel mesh onto attention layers that
        carry the ``_ring_mesh`` socket when a ``sep`` strategy is armed.
        Returns the model."""
        for name, p in model.named_parameters():
            spec = self.spec_for(name, p.shape)
            if any(s is not None for s in spec):
                p._data = jax.device_put(
                    p._data, NamedSharding(self.mesh, spec))
        if self.sep_impl is not None:
            for _, sub in model.named_sublayers(include_self=True):
                if hasattr(sub, "_ring_mesh"):
                    sub._ring_mesh = self.mesh
        return model

    # -- identity -------------------------------------------------------
    def describe(self):
        """Stable human-readable description (also the fingerprint
        preimage)."""
        axes = mesh_axes(self.mesh)
        lines = ["mesh: " + ",".join(f"{a}={n}" for a, n in axes.items())]
        for pattern, spec in self.param_rules:
            lines.append(f"param {pattern} -> {spec!r}")
        if self.data_dims:
            lines.append("data " + ",".join(
                f"{d}:{a}" for d, a in sorted(self.data_dims.items())))
        if self.moment_axis:
            lines.append(f"moments dim0 -> {self.moment_axis}")
        if self.param_fallback_axis:
            lines.append(f"param fallback dim0 -> "
                         f"{self.param_fallback_axis}")
        if self.sep_impl:
            lines.append(f"sep: {self.sep_impl} over {self.sep_axis}")
        if self.pp_stages:
            lines.append(f"pp: {self.pp_stages} stages")
        for name, kwargs in self.strategies:
            lines.append(f"strategy {name} "
                         + ",".join(f"{k}={v}" for k, v in
                                    sorted(kwargs.items())))
        return "\n".join(lines)

    def fingerprint(self):
        """``{"mesh": {...}, "digest": sha1}`` — what the checkpoint layer
        records; the digest covers mesh shape AND the full rule/strategy
        table."""
        digest = hashlib.sha1(self.describe().encode()).hexdigest()
        return {"mesh": mesh_axes(self.mesh), "digest": digest}

    def __repr__(self):
        axes = mesh_axes(self.mesh)
        strat = ",".join(n for n, _ in self.strategies) or "none"
        return (f"Plan(mesh={{{', '.join(f'{a}:{n}' for a, n in axes.items())}}}, "
                f"strategies=[{strat}], rules={len(self.param_rules)})")

    def scoped(self, prefix):
        """A view of this plan for a model whose parameter names carry an
        extra ``prefix``: name-keyed rule lookups strip the prefix before
        matching, so a rule table anchored at the network root
        (``"llama.layers.*"``) keeps matching when an adopter wraps the
        network in an outer module (hapi's planned path wraps network +
        loss in one ``_NetLoss``, prefixing every name with ``"net."``).
        Mesh, rules, strategies and fingerprint are the wrapped plan's own
        (shared, not copied)."""
        return _ScopedPlanView(self, str(prefix))


class _ScopedPlanView(Plan):
    """See :meth:`Plan.scoped`. Shares ALL state with the wrapped plan —
    attribute reads fall through via ``__getattr__`` — and overrides only
    the two name-pattern matchers; every inherited method
    (``moment_spec_for``, ``sharding_for``, ``apply_to_model``, ...)
    resolves names through those overrides."""

    def __init__(self, base, prefix):   # deliberately no Plan.__init__
        self._base_plan = base
        self._name_prefix = prefix

    def __getattr__(self, attr):
        return getattr(object.__getattribute__(self, "_base_plan"), attr)

    def _strip(self, name):
        p = self._name_prefix
        return name[len(p):] if name.startswith(p) else name

    def spec_for(self, name, shape):
        return self._base_plan.spec_for(self._strip(name), shape)

    def rule_dims(self, name):
        return self._base_plan.rule_dims(self._strip(name))

    def scoped(self, prefix):
        return _ScopedPlanView(self._base_plan,
                               str(prefix) + self._name_prefix)

    def __repr__(self):
        return (f"{Plan.__repr__(self)}.scoped({self._name_prefix!r})")
