"""``compile_step_with_plan`` — the one compile layer for planned steps.

Everything data-parallel-ish lowers through ``jax.jit`` with
``in_shardings``/``out_shardings`` built from the plan (GSPMD partitions
the body); only the attention collectives — the ppermute ring rotation and
the Ulysses all_to_all head/seq re-shard, which GSPMD cannot express —
drop to ``shard_map``, and they do so INSIDE the model ops
(``ring_flash_attention`` / ``sep_all_to_all_attention``), not here: a
planned step containing sep attention is still one ``jax.jit`` whose trace
embeds the manual region. That split (pjit outside, shard_map only for
collectives) is the SNIPPETS [1][3] pattern and is documented in
DESIGN_DECISIONS.md "Sharding plans".

Spec trees passed here are *prefix pytrees* of the function arguments (the
``jax.jit`` contract): a leaf may be ``None`` (leave jax to infer from the
committed argument placement), a ``PartitionSpec`` (resolved over the plan
mesh) or a ready ``Sharding``.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["compile_step_with_plan"]


def _resolve_tree(plan, tree):
    """Map ``PartitionSpec`` leaves to ``NamedSharding`` over the plan
    mesh; ``None`` holes and ready ``Sharding`` leaves pass through.
    Tuples/lists/dicts are containers (the jax.jit prefix-pytree
    convention) — spec leaves must be ``PartitionSpec``, never bare
    tuples, so containers and specs cannot be confused."""
    if tree is None:
        return None

    def is_leaf(x):
        return x is None or isinstance(x, (P, jax.sharding.Sharding))

    def conv(x):
        if isinstance(x, P):
            return NamedSharding(plan.mesh, x)
        return x

    return jax.tree.map(conv, tree, is_leaf=is_leaf)


def compile_step_with_plan(fn, plan=None, *, in_specs=None, out_specs=None,
                           donate_argnums=(), static_argnums=(), name=None):
    """Compile ``fn`` under a :class:`~.plan.Plan`.

    - ``plan=None`` (or a 1-device mesh): plain ``jax.jit`` — single-device
      deployments and the planned path share this one entry point, so there
      is no strategy-specific compile fork at the call sites.
    - ``in_specs``/``out_specs``: prefix pytrees of PartitionSpecs (or
      ``None`` holes) resolved over ``plan.mesh``.
    - ``name``: register compile/hit telemetry for this executable under
      ``paddle.jit.cache_stats()[name]`` (the serving engine's CountingJit
      contract). The returned object then exposes ``__call__`` with
      counting; without ``name`` the raw ``jax.jit`` function (with
      ``.lower``) is returned.
    """
    kwargs = dict(donate_argnums=tuple(donate_argnums),
                  static_argnums=tuple(static_argnums))
    if plan is not None and plan.mesh.devices.size > 1:
        ins = _resolve_tree(plan, in_specs)
        outs = _resolve_tree(plan, out_specs)
        if ins is not None:
            kwargs["in_shardings"] = ins
        if outs is not None:
            kwargs["out_shardings"] = outs
    if name is None:
        return jax.jit(fn, **kwargs)
    from ...jit.cache import CountingJit

    return CountingJit(fn, name,
                       static_argnums=kwargs.pop("static_argnums"),
                       donate_argnums=kwargs.pop("donate_argnums"),
                       jit_kwargs=kwargs)
