"""Unified sharding plans: one mesh/spec API driving training, hapi and
serving (ROADMAP item 3).

    from paddle_tpu.distributed.plan import Plan

    plan = Plan.build({"dp": 2, "tp": 2}, ["dp", "tp", "zero1"])
    step = FusedTrainStep(model, opt, plan=plan)          # training
    Model(net).prepare(opt, loss, plan=plan).fit(ds)      # hapi
    LLMEngine(model, plan=plan)                           # serving

See DESIGN_DECISIONS.md "Sharding plans" for the why, and
README.md's multichip recipe for the CPU-virtual-device workflow.
"""

from .compile import compile_step_with_plan  # noqa: F401
from .mesh import AXES, make_mesh, mesh_axes  # noqa: F401
from .plan import Plan, PlanError  # noqa: F401
from .strategies import STRATEGIES, apply, register_strategy  # noqa: F401

__all__ = [
    "AXES", "Plan", "PlanError", "STRATEGIES", "apply",
    "compile_step_with_plan", "make_mesh", "mesh_axes",
    "register_strategy",
]
