"""Process groups.

Reference: python/paddle/distributed/collective.py (Group registry, new_group)
+ C++ ProcessGroup (paddle/fluid/distributed/collective/process_group.h:47).

TPU-native design (SURVEY.md §5.8): a Group is a *view over a mesh axis* of
the global device mesh — there is no communicator object to create. Inside
traced code (shard_map/jit) collectives lower to lax.p* ops over the group's
axis name; eagerly, a collective over arrays sharded on the group axis is a
device_put-induced XLA collective.
"""

from __future__ import annotations

import numpy as np
import jax

__all__ = ["Group", "new_group", "get_group", "destroy_process_group",
           "is_available", "_set_default_group", "_get_default_group",
           "_get_global_group"]

_group_registry: dict[int, "Group"] = {}
_default_group: "Group | None" = None
_next_gid = 0


class Group:
    """A collective group = ordered rank list + (optionally) the mesh axis it
    corresponds to."""

    def __init__(self, ranks, gid=None, axis_name=None, mesh=None, pg=None):
        global _next_gid
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.id = gid if gid is not None else _next_gid
        _next_gid = max(_next_gid, self.id + 1)
        self.axis_name = axis_name  # mesh axis this group spans (traced path)
        self.mesh = mesh  # jax Mesh or ProcessMesh
        self.pg = pg

    @property
    def rank(self):
        import jax

        pid = jax.process_index()
        return self.ranks.index(pid) if pid in self.ranks else 0

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self.pg

    def __repr__(self):
        return (f"Group(id={self.id}, nranks={self.nranks}, "
                f"axis={self.axis_name})")


def _set_default_group(group):
    global _default_group
    _default_group = group
    _group_registry[group.id] = group


def _get_default_group():
    global _default_group
    if _default_group is None:
        n = jax.device_count()
        _set_default_group(Group(list(range(n)), gid=0, axis_name=None))
    return _default_group


_get_global_group = _get_default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None,
              mesh=None):
    """Reference: collective.py new_group. With a mesh-axis view there is no
    communicator bootstrap; the group is just registered."""
    if ranks is None:
        ranks = list(range(jax.device_count()))
    g = Group(sorted(ranks), axis_name=axis_name, mesh=mesh)
    _group_registry[g.id] = g
    return g


def get_group(gid=0):
    return _group_registry.get(gid)


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _group_registry.clear()
        _default_group = None
    else:
        _group_registry.pop(group.id, None)


def is_available():
    return True
