"""Auto-parallel Engine — the `auto.Engine(model, loss, opt).fit()` surface.

Reference: python/paddle/distributed/auto_parallel/static/engine.py (Engine
:59, .fit :911) driving the Completer -> Partitioner -> Resharder -> passes
pipeline (SURVEY §3.5).

TPU-native collapse: that whole pipeline IS GSPMD. The user marks tensors
with ``shard_tensor`` / ``shard_layer`` (sharding annotations); the Engine
builds ONE donated, fused train-step executable
(incubate.FusedTrainStep) and feeds it mesh-sharded batches — XLA performs
completion (sharding propagation), partitioning (SPMD lowering), and
resharding (collective insertion) during compilation. Completer/Partitioner/
Resharder have no runtime object to expose because they run inside the
compiler.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .process_mesh import ProcessMesh

__all__ = ["Engine", "Strategy"]


class Strategy:
    """auto_parallel Strategy (ref auto_parallel/strategy.py): a dataclass-ish
    config bag; the toggles that matter on TPU are consumed here (amp ->
    bf16 params, gradient_merge -> accumulate steps)."""

    def __init__(self, config=None):
        self.auto_mode = "semi"
        self.seed = None
        self.amp = _Bag(enable=False, dtype="bfloat16", level="O2")
        self.recompute = _Bag(enable=False)
        self.gradient_merge = _Bag(enable=False, k_steps=1, avg=True)
        self.pipeline = _Bag(enable=False)
        self.sharding = _Bag(enable=False, stage=1, degree=-1)
        if config:
            for k, v in config.items():
                if isinstance(v, dict):
                    # merge into the sub-config bag (attribute access form)
                    bag = getattr(self, k, None)
                    if isinstance(bag, _Bag):
                        bag.__dict__.update(v)
                    else:
                        setattr(self, k, _Bag(**v))
                else:
                    setattr(self, k, v)


class _Bag:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class Engine:
    """ref engine.py:59 — Engine(model, loss, optimizer, metrics, strategy)."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = (metrics if isinstance(metrics, (list, tuple))
                         else [metrics]) if metrics else []
        self._strategy = strategy or Strategy()
        self._step = None
        self._mesh = None
        self.history = {}

    # ---- mesh / data placement ----------------------------------------
    def _resolve_mesh(self):
        if self._mesh is not None:
            return self._mesh
        from .process_mesh import get_mesh

        mesh = None
        try:
            mesh = get_mesh()
        except Exception:
            mesh = None
        if mesh is None:
            # every addressable device on one data axis
            n = jax.device_count()
            mesh = ProcessMesh(np.arange(n).tolist(), dim_names=["dp"])
        self._mesh = mesh
        return mesh

    def _shard_batch(self, arrs):
        """dp-shard the batch dim over the mesh's first axis."""
        mesh = self._resolve_mesh()
        axis = mesh.dim_names[0]
        out = []
        for a in arrs:
            arr = a._data if isinstance(a, Tensor) else np.asarray(a)
            spec = [None] * arr.ndim
            if arr.ndim and arr.shape[0] % mesh.jax_mesh.shape[axis] == 0:
                spec[0] = axis
            out.append(Tensor(jax.device_put(
                np.asarray(arr),
                NamedSharding(mesh.jax_mesh, P(*spec)))))
        return out

    # ---- build ---------------------------------------------------------
    def _build_step(self):
        if self._step is not None:
            return self._step
        from ... import nn
        from ...incubate import FusedTrainStep

        model, loss = self._model, self._loss

        class WithLoss(nn.Layer):
            def __init__(self):
                super().__init__()
                self.inner = model

            def forward(self, *args):
                *ins, label = args
                out = self.inner(*ins)
                return loss(out, label)

        if self._strategy.amp.enable and \
                self._strategy.amp.dtype == "bfloat16":
            model.bfloat16()
        self._with_loss = WithLoss() if loss is not None else model
        gm = self._strategy.gradient_merge
        if getattr(gm, "enable", False) and int(gm.k_steps) > 1:
            # gradient merge needs grads to live across micro-steps, which
            # the donated fused step doesn't do — run the eager accumulate
            # loop (still jit-cached per op) and apply every k_steps
            k = int(gm.k_steps)
            avg = bool(getattr(gm, "avg", True))
            opt = self._optimizer
            counter = {"n": 0}

            def eager_step(*batch):
                loss = self._with_loss(*batch)
                loss.backward()
                counter["n"] += 1
                if counter["n"] % k == 0:
                    if avg:
                        for p in opt._parameter_list:
                            if p.grad is not None:
                                p.grad._rebind(p.grad._data / k)
                    opt.step()
                    opt.clear_grad()
                return loss

            self._step = eager_step
        else:
            self._step = FusedTrainStep(self._with_loss, self._optimizer)
        return self._step

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train",
                init_parameters=True):
        """ref engine.py prepare — here compilation is lazy (first batch
        fixes the shapes), so prepare only resolves the mesh."""
        self._resolve_mesh()
        return self

    # ---- loops ---------------------------------------------------------
    def _loader(self, data, batch_size):
        from ...io import DataLoader

        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=False,
                          drop_last=True)

    def fit(self, train_data=None, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, save_dir=None,
            save_freq=1, valid_data=None, valid_sample_split=None,
            valid_freq=1, valid_steps=None, collate_fn=None, callbacks=None,
            verbose=1, nvprof_range=None):
        """ref engine.py:911. Returns a history dict of per-epoch losses."""
        assert self._optimizer is not None, "Engine needs an optimizer"
        step = self._build_step()
        loader = self._loader(train_data, batch_size)
        history = {"loss": []}
        for epoch in range(epochs):
            losses = []
            for i, batch in enumerate(loader):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                sharded = self._shard_batch(batch)
                loss = step(*sharded)
                losses.append(float(loss.numpy()))
                if verbose and log_freq and (i + 1) % log_freq == 0:
                    print(f"epoch {epoch} step {i + 1} "
                          f"loss {np.mean(losses[-log_freq:]):.5f}")
            history["loss"].append(float(np.mean(losses)) if losses
                                   else None)
            if valid_data is not None and (epoch + 1) % valid_freq == 0:
                history.setdefault("valid_loss", []).append(
                    self.evaluate(valid_data, batch_size=batch_size,
                                  verbose=0)["loss"])
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch{epoch}")
        self.history = history
        return history

    def evaluate(self, valid_data=None, valid_sample_split=None, batch_size=1,
                 steps=None, log_freq=10, collate_fn=None, callbacks=None,
                 verbose=1):
        loader = self._loader(valid_data, batch_size)
        self._model.eval()
        losses = []
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            sharded = self._shard_batch(batch)
            *ins, label = sharded
            out = self._model(*ins)
            loss = self._loss(out, label) if self._loss is not None else out
            losses.append(float(loss.numpy()))
        self._model.train()
        return {"loss": float(np.mean(losses)) if losses else None}

    def _forward_arity(self):
        """Required positional-arg count of the network forward, or None."""
        import inspect

        try:
            sig = inspect.signature(self._model.forward)
            return len([p for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)
                        and p.default is p.empty])
        except (TypeError, ValueError):
            return None

    def predict(self, test_data=None, test_sample_split=None, batch_size=1,
                steps=None, collate_fn=None, callbacks=None, verbose=1):
        loader = self._loader(test_data, batch_size)
        self._model.eval()
        outs = []
        npos = self._forward_arity()
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            sharded = self._shard_batch(batch)
            if test_sample_split is not None:
                sharded = sharded[:int(test_sample_split)]
            elif self._loss is not None and len(sharded) >= 2:
                # drop trailing label slots only when the batch is wider than
                # the network forward's positional arity (a multi-input
                # unlabeled dataset must keep every element)
                if npos is None or len(sharded) > npos:
                    sharded = sharded[:-1]
            outs.append(self._model(*sharded).numpy())
        self._model.train()
        return outs

    # ---- persistence ----------------------------------------------------
    def save(self, path, training=True):
        import os

        from ...framework.io import save

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        save(self._model.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        import os

        from ...framework.io import load

        self._model.set_state_dict(load(path + ".pdparams"))
        if load_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(load(path + ".pdopt"))

    # ---- introspection (reference parity) -------------------------------
    def main_program(self, mode="train"):
        """The reference returns the partitioned Program; the analog here is
        the compiled step's HLO (one program, all ranks)."""
        if self._step is None:
            raise RuntimeError("call fit()/prepare() first")
        return "<compiled XLA executable (GSPMD-partitioned)>"

    @property
    def mesh(self):
        return self._resolve_mesh()
