"""Semi-automatic SPMD parallel API.

Reference: python/paddle/distributed/auto_parallel/api.py (shard_tensor :118,
reshard :288, shard_layer :387) + C++ DistTensor
(paddle/phi/core/distributed/auto_parallel/dist_tensor.h:39), SPMD rules
(paddle/phi/infermeta/spmd_rules/), reshard kernels
(.../auto_parallel/reshard/).

TPU-native design (SURVEY.md §7.1): DistTensor ≡ a jax.Array with a
NamedSharding; SPMD rule propagation ≡ GSPMD; the reference's 9 hand-written
reshard functions ({r,s,p}_to_{r,s,p}) ≡ one device_put/with_sharding_constraint
— XLA emits the collective (all_gather for s→r, reduce for p→r, slice for
r→s, ...) that the reference implements by hand per case.
"""

from .placement import Partial, Placement, Replicate, Shard  # noqa: F401
from .process_mesh import ProcessMesh, get_mesh, set_mesh  # noqa: F401
from .engine import Engine, Strategy  # noqa: F401
from .api import (  # noqa: F401
    dtensor_from_fn, reshard, shard_layer, shard_optimizer, shard_tensor,
    unshard_dtensor,
)
