"""ProcessMesh — device mesh wrapper.

Reference: python/paddle/distributed/auto_parallel/process_mesh.py +
paddle/phi/core/distributed/auto_parallel/process_mesh.h. Wraps a
jax.sharding.Mesh (AxisType.Auto so GSPMD propagates shardings).
"""

from __future__ import annotations

import numpy as np
import jax

__all__ = ["ProcessMesh", "get_mesh", "set_mesh"]

_global_mesh: "ProcessMesh | None" = None


def _pick_devices(n):
    devs = jax.devices()
    if len(devs) < n:
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n:
                return cpu[:n]
        except RuntimeError:
            pass
        raise ValueError(f"mesh needs {n} devices, only {len(devs)} available")
    return devs[:n]


class ProcessMesh:
    def __init__(self, mesh=None, dim_names=None, shape=None):
        if isinstance(mesh, jax.sharding.Mesh):
            self._jax_mesh = mesh
            self._shape = tuple(mesh.devices.shape)
            self._dim_names = tuple(mesh.axis_names)
            self._process_ids = [d.id for d in mesh.devices.flat]
            return
        if mesh is not None:
            arr = np.asarray(mesh)
            shape = arr.shape
            process_ids = arr.reshape(-1).tolist()
        else:
            assert shape is not None
            shape = tuple(shape)
            process_ids = list(range(int(np.prod(shape))))
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(len(shape))]
        self._shape = tuple(int(s) for s in shape)
        self._dim_names = tuple(dim_names)
        self._process_ids = process_ids
        all_devices = {d.id: d for d in jax.devices()}
        if not all(pid in all_devices for pid in process_ids):
            try:
                for d in jax.devices("cpu"):
                    all_devices.setdefault(d.id, d)
            except RuntimeError:
                pass
        if all(pid in all_devices for pid in process_ids):
            devs = np.array([all_devices[p] for p in process_ids],
                            dtype=object).reshape(self._shape)
        else:
            # abstract mesh (more processes than local devices — multi-host
            # compile-only contexts)
            devs = np.array(_pick_devices(int(np.prod(self._shape))),
                            dtype=object).reshape(self._shape)
        self._jax_mesh = jax.sharding.Mesh(
            devs, self._dim_names,
            axis_types=(jax.sharding.AxisType.Auto,) * len(self._shape))

    # ---- paddle API surface ----
    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return list(self._process_ids)

    @property
    def mesh(self):
        return np.asarray(self._process_ids).reshape(self._shape)

    @property
    def jax_mesh(self) -> jax.sharding.Mesh:
        return self._jax_mesh

    def get_dim_size(self, dim_name):
        return self._shape[self._dim_names.index(dim_name)]

    def get_mesh_with_dim(self, dim_name, index=None):
        """Sub-mesh along one axis (reference process_mesh.py API)."""
        axis = self._dim_names.index(dim_name)
        arr = self.mesh
        moved = np.moveaxis(arr, axis, 0)
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        pm = ProcessMesh(moved, names)
        if index is not None:
            sub = moved[index]
            return ProcessMesh(sub, names[1:])
        return pm

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._shape == other._shape
                and self._dim_names == other._dim_names
                and self._process_ids == other._process_ids)

    def __hash__(self):
        return hash((self._shape, self._dim_names, tuple(self._process_ids)))

    def __repr__(self):
        return (f"ProcessMesh(shape={list(self._shape)}, "
                f"dim_names={list(self._dim_names)})")


def set_mesh(mesh: ProcessMesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> "ProcessMesh | None":
    return _global_mesh
