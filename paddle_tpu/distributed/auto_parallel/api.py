"""shard_tensor / reshard / shard_layer / shard_optimizer.

Reference: python/paddle/distributed/auto_parallel/api.py (shard_tensor :118,
reshard :288, shard_layer :387, shard_optimizer and dist to_static :1338).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...core import state
from ...core.tensor import Parameter, Tensor
from .placement import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = ["shard_tensor", "reshard", "shard_layer", "shard_optimizer",
           "dtensor_from_fn", "unshard_dtensor", "placements_to_spec"]


def placements_to_spec(placements, ndim=None):
    """[Shard(0), Replicate()] over mesh dims -> PartitionSpec on tensor dims."""
    dim_axes = {}
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            dim_axes.setdefault(pl.dim, []).append(mesh_dim)
    n = ndim if ndim is not None else (
        max(dim_axes.keys(), default=-1) + 1)
    axes = []
    for d in range(n):
        mds = dim_axes.get(d)
        axes.append(None if not mds else mds)
    return axes


def _named_sharding(mesh: ProcessMesh, placements, ndim):
    names = mesh.dim_names
    dim_axes = placements_to_spec(placements, ndim)
    spec = []
    for entry in dim_axes:
        if entry is None:
            spec.append(None)
        elif len(entry) == 1:
            spec.append(names[entry[0]])
        else:
            spec.append(tuple(names[m] for m in entry))
    return NamedSharding(mesh.jax_mesh, PartitionSpec(*spec))


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    """Reference api.py:118. Returns a Tensor whose array carries a
    NamedSharding (the DistTensor analog)."""
    if isinstance(data, Tensor):
        t = data
    else:
        t = Tensor(data, dtype=dtype)
    sharding = _named_sharding(mesh, placements, t._data.ndim)
    if isinstance(t._data, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(t._data, sharding)
        out = Tensor._wrap(arr)
        out.stop_gradient = t.stop_gradient
    elif any(isinstance(p, Partial) for p in placements):
        # Partial is only meaningful inside traced code; eagerly it's the
        # value itself (single-controller holds the already-reduced value)
        out = t
    else:
        arr = jax.device_put(t._data, sharding)
        if isinstance(t, Parameter) or not t.is_leaf:
            t._data = arr
            out = t
        else:
            out = Tensor._wrap(arr)
            out.stop_gradient = t.stop_gradient if stop_gradient is None \
                else stop_gradient
    out._placement = (mesh, tuple(placements))
    return out


def reshard(dist_tensor, mesh: ProcessMesh, placements):
    """Reference api.py:288 + reshard kernels. One call covers every
    {r,s,p}→{r,s,p} transition: XLA inserts the matching collective."""
    sharding = _named_sharding(mesh, placements, dist_tensor._data.ndim)
    if isinstance(dist_tensor._data, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(dist_tensor._data, sharding)
    else:
        arr = jax.device_put(dist_tensor._data, sharding)
    out = Tensor._wrap(arr)
    out.stop_gradient = dist_tensor.stop_gradient
    out._placement = (mesh, tuple(placements))
    return out


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Reference api.py:387 — apply shard_fn(name, layer, mesh) to every
    sublayer; default replicates every parameter over the mesh."""

    def default_shard_fn(name, sublayer, mesh):
        for pname, p in sublayer._parameters.items():
            if p is not None and p._placement is None:
                shard_tensor(p, mesh,
                             [Replicate() for _ in range(mesh.ndim)])

    fn = shard_fn or default_shard_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """Reference api.py shard_optimizer — states inherit each param's
    sharding automatically here (accumulators are created zeros_like the
    sharded param array), so this is mostly API parity."""
    return optimizer


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def unshard_dtensor(dist_tensor):
    arr = dist_tensor._data
    if hasattr(arr, "sharding"):
        devs = list(arr.devices()) if hasattr(arr, "devices") else None
        arr = jax.device_put(
            arr, jax.sharding.SingleDeviceSharding(
                devs[0] if devs else jax.devices()[0]))
    out = Tensor._wrap(arr)
    out.stop_gradient = dist_tensor.stop_gradient
    return out
