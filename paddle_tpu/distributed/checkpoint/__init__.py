"""Distributed checkpoint: sharded save + reshard-on-load.

Reference: python/paddle/distributed/checkpoint/ — save_state_dict
(save_state_dict.py:104, per-rank shard files + global metadata),
load_state_dict (load_state_dict.py:365, reshards across changed meshes),
metadata.py (tensor -> shard-index map).

TPU-native: arrays already carry their sharding (NamedSharding). Save writes
one file per *local shard set* (single-controller: per process) plus a
metadata json describing each tensor's global shape, dtype and the shard
layout; load reassembles the global tensor and device_puts onto the target
placement — reshard-on-load across different meshes/degrees is therefore the
same code path as same-mesh load. Layout matches what an Orbax-style
TensorStore backend would need, without the dependency.
"""

from __future__ import annotations

import json
import os

import numpy as np
import jax

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _shard_infos(arr):
    """List of (device_id, index-slices, shape) for every addressable shard."""
    infos = []
    if isinstance(arr, jax.Array) and hasattr(arr, "addressable_shards"):
        for sh in arr.addressable_shards:
            idx = []
            for s in sh.index:
                start = 0 if s.start is None else int(s.start)
                stop = None if s.stop is None else int(s.stop)
                idx.append([start, stop])
            infos.append({"device": sh.device.id, "index": idx,
                          "replica_id": sh.replica_id})
    return infos


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Reference save_state_dict.py:104."""
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    metadata = {"state": {}, "version": 1}
    payload = {}
    for name, t in state_dict.items():
        arr = t._data if isinstance(t, Tensor) else np.asarray(t)
        shards = _shard_infos(arr) if isinstance(arr, jax.Array) else []
        # single-controller: save unique (replica 0) shards only
        saved = []
        if shards and any(s["replica_id"] == 0 for s in shards):
            for i, sh in enumerate(
                    s for s in shards if s["replica_id"] == 0):
                key = f"{name}@shard{i}"
                idx = tuple(slice(a, b) for a, b in sh["index"])
                payload[key] = np.asarray(arr[idx])
                saved.append({"key": key, "index": sh["index"]})
        else:
            key = f"{name}@full"
            payload[key] = np.asarray(arr)
            saved.append({"key": key, "index": None})
        metadata["state"][name] = {
            "global_shape": list(np.shape(arr)),
            "dtype": str(np.asarray(payload[saved[0]["key"]]).dtype),
            "shards": saved,
        }
    np.savez(os.path.join(path, f"rank{rank}.npz"), **payload)
    if rank == coordinator_rank:
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(metadata, f)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False):
    """Reference load_state_dict.py:365 — fills `state_dict` tensors in
    place, resharding to each tensor's current placement."""
    with open(os.path.join(path, "metadata.json")) as f:
        metadata = json.load(f)
    files = [np.load(os.path.join(path, fn))
             for fn in sorted(os.listdir(path)) if fn.endswith(".npz")]

    def find(key):
        for f in files:
            if key in f:
                return f[key]
        raise KeyError(key)

    for name, t in state_dict.items():
        if name not in metadata["state"]:
            continue
        info = metadata["state"][name]
        full = np.zeros(info["global_shape"],
                        dtype=np.dtype(info["dtype"]))
        if full.ndim == 0:
            full = np.asarray(find(info["shards"][0]["key"]))
        else:
            for sh in info["shards"]:
                data = find(sh["key"])
                if sh["index"] is None:
                    full = np.asarray(data)
                else:
                    idx = tuple(slice(a, b) for a, b in sh["index"])
                    full[idx] = data
        arr = t._data
        target_sharding = getattr(arr, "sharding", None)
        import jax.numpy as jnp

        new = jnp.asarray(full, arr.dtype)
        if target_sharding is not None and isinstance(
                target_sharding, jax.sharding.NamedSharding):
            new = jax.device_put(new, target_sharding)
        t._rebind(new.reshape(arr.shape))
    return state_dict
