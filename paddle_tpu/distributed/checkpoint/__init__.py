"""Distributed checkpoint: sharded save + reshard-on-load.

Reference: python/paddle/distributed/checkpoint/ — save_state_dict
(save_state_dict.py:104, per-rank shard files + global metadata),
load_state_dict (load_state_dict.py:365, reshards across changed meshes),
metadata.py (tensor -> shard-index map).

TPU-native: arrays already carry their sharding (NamedSharding). Each
process writes one ``rank{r}.npz`` payload with **rank-namespaced** shard
keys plus a ``rank{r}.meta.json`` fragment describing its shards; load
merges *all* fragments found under the path, so a multi-host save needs no
cross-host metadata gather (the reference gathers to the coordinator; here
the shared checkpoint directory is the rendezvous). The coordinator also
writes its own fragment as ``metadata.json`` for API parity, but load never
depends on it; stale higher-rank fragments from a previous larger-world
save are removed by the coordinator. Reassembly + ``device_put`` onto the target placement makes
reshard-on-load across different meshes/degrees the same code path as
same-mesh load.

Extended dtypes (bfloat16, float8_*) are stored as same-width unsigned
integers — ``np.savez`` silently degrades ml_dtypes arrays to void — and
reinterpreted on load via the dtype string recorded in the metadata.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import jax

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "AsyncSaveHandle"]


class AsyncSaveHandle:
    """Completion handle for ``save_state_dict(..., async_save=True)``.

    The device->host snapshot happens synchronously inside save_state_dict
    (so training may mutate/donate the buffers immediately after it
    returns); only the disk write runs on this background thread. Orbax
    (the TPU-idiomatic checkpointer) calls the same shape
    ``AsyncCheckpointer.save`` + ``wait_until_finished``.
    """

    def __init__(self, thread, errbox, path=None):
        self._thread = thread
        self._errbox = errbox
        self._path = path

    def done(self):
        return not self._thread.is_alive()

    def wait(self, timeout=None):
        """Block until the write completes; re-raises a write failure (once
        — a waited handle is retired, so a later unrelated save does not
        re-raise it)."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("async checkpoint write still in flight")
        if self in _IN_FLIGHT:
            _IN_FLIGHT.remove(self)
        if self._errbox:
            err = self._errbox[0]
            self._errbox = []
            raise err
    result = wait


_IN_FLIGHT: list = []  # AsyncSaveHandle s not yet waited on


def _drain_in_flight():
    """A new save waits for prior async writes (reference
    save_state_dict.py:104 waits on its async executor the same way) so two
    saves to one path can't interleave. A PRIOR save's write failure is
    surfaced as a loud warning, not an exception — it must not abort the
    new, unrelated save (the user can still catch it via that handle's own
    ``wait()``)."""
    import warnings

    while _IN_FLIGHT:
        h = _IN_FLIGHT.pop()
        try:
            h.wait()
        except Exception as e:
            warnings.warn(
                f"a previous async checkpoint save to {h._path!r} FAILED: "
                f"{type(e).__name__}: {e} — that checkpoint is incomplete",
                stacklevel=3)


import atexit  # noqa: E402

atexit.register(_drain_in_flight)  # never exit with a write mid-file

_UINT_FOR_WIDTH = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _np_dtype(name):
    """Resolve a dtype string incl. ml_dtypes extended types."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _storable(data):
    """(array-as-native-dtype, true-dtype-string). np.savez only round-trips
    builtin numpy dtypes; view extended dtypes as same-width uints."""
    dt = data.dtype
    if dt.kind in "biufc":  # native numpy types round-trip as-is
        return data, dt.name
    return data.view(_UINT_FOR_WIDTH[dt.itemsize]), dt.name


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Reference save_state_dict.py:104. With ``async_save=True`` the
    device->host snapshot is taken before returning and the disk write runs
    on a background thread; returns an :class:`AsyncSaveHandle` (sync saves
    return None)."""
    _drain_in_flight()
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    nprocs = jax.process_count()
    if rank == coordinator_rank:
        # remove fragments from a previous save with more ranks — they are
        # not overwritten below and _merged_metadata would read stale shards
        import re

        for fn in os.listdir(path):
            m = re.match(r"rank(\d+)\.(npz|meta\.json)$", fn)
            if m and int(m.group(1)) >= nprocs:
                os.remove(os.path.join(path, fn))
    fragment = {"state": {}, "version": 2, "rank": rank,
                "world_size": nprocs}
    payload = {}
    for name, t in state_dict.items():
        arr = t._data if isinstance(t, Tensor) else np.asarray(t)
        # single-controller: save unique (replica 0) shards only, reading
        # each shard's device-local buffer directly (no cross-device gather)
        saved = []
        true_dtype = None
        shards = (list(arr.addressable_shards)
                  if isinstance(arr, jax.Array)
                  and hasattr(arr, "addressable_shards") else [])
        if shards and not any(s.replica_id == 0 for s in shards):
            # every addressable shard is a replica of data whose replica-0
            # copy lives on another process (e.g. tp-sharded within hosts,
            # replicated across the host axis): that rank saves it; writing
            # the global array here would need a cross-host gather
            continue
        if shards and any(s.replica_id == 0 for s in shards):
            for i, sh in enumerate(
                    s for s in shards if s.replica_id == 0):
                key = f"{name}@r{rank}s{i}"
                index = [[0 if s.start is None else int(s.start),
                          None if s.stop is None else int(s.stop)]
                         for s in sh.index]
                data, true_dtype = _storable(np.asarray(sh.data))
                payload[key] = data
                saved.append({"key": key, "index": index})
        else:
            key = f"{name}@r{rank}full"
            data, true_dtype = _storable(np.asarray(arr))
            payload[key] = data
            saved.append({"key": key, "index": None})
        fragment["state"][name] = {
            "global_shape": list(np.shape(arr)),
            "dtype": true_dtype,
            "shards": saved,
        }
    def write():
        # payload arrays are host copies (np.asarray above) — training may
        # have moved on; write shards first, metadata fragments last so a
        # reader that sees the fragment also sees its shards
        np.savez(os.path.join(path, f"rank{rank}.npz"), **payload)
        with open(os.path.join(path, f"rank{rank}.meta.json"), "w") as f:
            json.dump(fragment, f)
        if rank == coordinator_rank:
            # API-parity marker only (the coordinator's own fragment); load
            # always merges rank*.meta.json fragments and never reads this
            with open(os.path.join(path, "metadata.json"), "w") as f:
                json.dump(fragment, f)

    if not async_save:
        write()
        return None

    errbox = []

    def run():
        try:
            write()
        except BaseException as e:  # surfaced on handle.wait()
            errbox.append(e)

    # non-daemon: interpreter exit joins the thread instead of killing the
    # write mid-file (plus the atexit drain above for belt and braces)
    thread = threading.Thread(target=run, name="ckpt-async-save",
                              daemon=False)
    thread.start()
    handle = AsyncSaveHandle(thread, errbox, path=path)
    _IN_FLIGHT.append(handle)
    return handle


def _merged_metadata(path):
    """Union of every rank's metadata fragment (shard lists concatenated)."""
    merged = {"state": {}}
    names = sorted(fn for fn in os.listdir(path)
                   if fn.endswith(".meta.json"))
    if not names:
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        if meta.get("version", 1) >= 2:
            # v2 metadata.json is one rank's fragment, not a merged view —
            # loading from it alone would silently zero other ranks' shards
            raise RuntimeError(
                f"checkpoint at {path} is missing its rank*.meta.json "
                "fragments (v2 layout); copy the full checkpoint directory")
        return meta
    for fn in names:
        with open(os.path.join(path, fn)) as f:
            frag = json.load(f)
        for name, info in frag["state"].items():
            if name not in merged["state"]:
                merged["state"][name] = {
                    "global_shape": info["global_shape"],
                    "dtype": info["dtype"],
                    "shards": [],
                }
            merged["state"][name]["shards"].extend(info["shards"])
    return merged


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False):
    """Reference load_state_dict.py:365 — fills `state_dict` tensors in
    place, resharding to each tensor's current placement."""
    metadata = _merged_metadata(path)
    files = [np.load(os.path.join(path, fn))
             for fn in sorted(os.listdir(path)) if fn.endswith(".npz")]

    def find(key, dtype):
        for f in files:
            if key in f:
                data = f[key]
                if data.dtype != dtype:
                    data = data.view(dtype)
                return data
        raise KeyError(key)

    for name, t in state_dict.items():
        if name not in metadata["state"]:
            continue
        info = metadata["state"][name]
        dtype = _np_dtype(info["dtype"])
        full = np.zeros(info["global_shape"], dtype=dtype)
        if full.ndim == 0:
            full = np.asarray(find(info["shards"][0]["key"], dtype))
        else:
            for sh in info["shards"]:
                data = find(sh["key"], dtype)
                if sh["index"] is None:
                    full = np.asarray(data)
                else:
                    idx = tuple(slice(a, b) for a, b in sh["index"])
                    full[idx] = data
        arr = t._data
        target_sharding = getattr(arr, "sharding", None)
        import jax.numpy as jnp

        new = jnp.asarray(full).astype(arr.dtype)
        if target_sharding is not None and isinstance(
                target_sharding, jax.sharding.NamedSharding):
            new = jax.device_put(new.reshape(arr.shape), target_sharding)
        t._rebind(new.reshape(arr.shape))
    return state_dict
