"""Distributed checkpoint: sharded save + reshard-on-load.

Reference: python/paddle/distributed/checkpoint/ — save_state_dict
(save_state_dict.py:104, per-rank shard files + global metadata),
load_state_dict (load_state_dict.py:365, reshards across changed meshes),
metadata.py (tensor -> shard-index map).

TPU-native: arrays already carry their sharding (NamedSharding). Each
process writes one ``rank{r}.npz`` payload with **rank-namespaced** shard
keys plus a ``rank{r}.meta.json`` fragment describing its shards; load
merges *all* fragments found under the path, so a multi-host save needs no
cross-host metadata gather (the reference gathers to the coordinator; here
the shared checkpoint directory is the rendezvous). The coordinator also
writes its own fragment as ``metadata.json`` for API parity, but load never
depends on it; stale higher-rank fragments from a previous larger-world
save are removed by the coordinator. Reassembly + ``device_put`` onto the target placement makes
reshard-on-load across different meshes/degrees the same code path as
same-mesh load.

Extended dtypes (bfloat16, float8_*) are stored as same-width unsigned
integers — ``np.savez`` silently degrades ml_dtypes arrays to void — and
reinterpreted on load via the dtype string recorded in the metadata.

Commit protocol (v3 layout): every shard payload and metadata fragment is
written tmp → fsync → atomic rename, each shard entry records a CRC32 of its
raw bytes in the rank's metadata fragment, and the coordinator writes a
``COMMIT`` sentinel (recording the saving world size) strictly last. A
directory without ``COMMIT`` is a torn save: ``load_state_dict`` raises
:class:`CheckpointCorruptionError` instead of silently zero-filling, and
``CheckpointManager.latest_valid_step`` skips it. CRC mismatches and
unreadable npz members raise the same typed error. Transient ``OSError``s
during the write retry with backoff (``FLAGS_ckpt_save_retries``).
"""

from __future__ import annotations

import json
import os
import threading
import zlib

import numpy as np
import jax

from ...core.tensor import Tensor
from ...framework.io import CheckpointCorruptionError

__all__ = ["save_state_dict", "load_state_dict", "AsyncSaveHandle",
           "CheckpointManager", "PlanMismatchError",
           "CheckpointCorruptionError", "is_committed",
           "verify_checkpoint", "sync_processes", "allgather_success",
           "allgather_ints"]

COMMIT_FILE = "COMMIT"


# ---------------------------------------------------------------------------
# cross-process sync primitives
#
# The commit protocol needs a barrier (no rank overwrites shards before the
# coordinator retracted the old COMMIT) and a success allgather (COMMIT only
# after every rank's write landed). XLA collectives
# (multihost_utils.sync_global_devices / process_allgather) are NOT
# available on the multi-process CPU backend — and a checkpoint barrier has
# no business running through the compiler anyway — so these go through
# jax.distributed's coordination service (the same service that did the
# rendezvous), falling back to the XLA path only when no coordination
# client exists.
# ---------------------------------------------------------------------------

import itertools  # noqa: E402

_SYNC_SEQ = itertools.count()  # ranks sync in program order, so a local
#                                counter stays aligned across processes
_SYNC_TIMEOUT_MS = 600_000


def _coord_client():
    try:
        from jax._src import distributed as jdist

        return jdist.global_state.client
    except Exception:
        return None


def sync_processes(tag):
    """Backend-agnostic cross-process barrier (no-op single-process)."""
    if jax.process_count() <= 1:
        return
    client = _coord_client()
    if client is None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)
        return
    name = f"pt_ckpt_sync:{next(_SYNC_SEQ)}:{zlib.crc32(tag.encode())}"
    client.wait_at_barrier(name, _SYNC_TIMEOUT_MS)


def allgather_success(ok, tag):
    """True iff EVERY process reports ``ok`` (a missing rank counts as
    failure); doubles as a barrier. Thin wrapper over the one
    coordination-service gather transport, :func:`allgather_ints`."""
    return all(v == 1 for v in allgather_ints(1 if ok else 0, tag))


def allgather_ints(value, tag):
    """Every process's ``value`` (an int), index-aligned by rank (a rank
    that never published stays ``None``); doubles as a barrier. The one
    gather transport over the coordination service — ``allgather_success``
    and the divergence sentinel's agreement checks (spike verdict,
    rollback TARGET step, budget admit bit) all ride it: a shared
    filesystem's attribute cache can show different ranks different
    HEALTHY markers, so the target must be agreed before any rank
    restores."""
    value = int(value)
    if jax.process_count() <= 1:
        return [value]
    client = _coord_client()
    if client is None:
        from jax.experimental import multihost_utils

        arr = multihost_utils.process_allgather(np.asarray([value]))
        return [int(v) for v in np.ravel(arr)]
    key = f"pt_ckpt_int:{next(_SYNC_SEQ)}:{zlib.crc32(tag.encode())}"
    client.key_value_set(f"{key}/{jax.process_index()}", str(value))
    client.wait_at_barrier(f"{key}.b", _SYNC_TIMEOUT_MS)
    vals = client.key_value_dir_get(f"{key}/")
    # clean the store once every rank has read: a long job checkpointing
    # for weeks must not grow the coordinator's memory one key per save
    client.wait_at_barrier(f"{key}.d", _SYNC_TIMEOUT_MS)
    if jax.process_index() == 0:
        try:
            client.key_value_delete(f"{key}/")
        except Exception:
            pass  # older runtimes without delete: stale keys are harmless
    out = [None] * jax.process_count()
    for path, v in vals:
        out[int(path.rsplit("/", 1)[-1])] = int(v)
    return out


class AsyncSaveHandle:
    """Completion handle for ``save_state_dict(..., async_save=True)``.

    The device->host snapshot happens synchronously inside save_state_dict
    (so training may mutate/donate the buffers immediately after it
    returns); only the disk write runs on this background thread. Orbax
    (the TPU-idiomatic checkpointer) calls the same shape
    ``AsyncCheckpointer.save`` + ``wait_until_finished``.
    """

    def __init__(self, thread, errbox, path=None):
        self._thread = thread
        self._errbox = errbox
        self._path = path

    def done(self):
        return not self._thread.is_alive()

    def wait(self, timeout=None):
        """Block until the write completes; re-raises a write failure (once
        — a waited handle is retired, so a later unrelated save does not
        re-raise it)."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("async checkpoint write still in flight")
        if self in _IN_FLIGHT:
            _IN_FLIGHT.remove(self)
        if self._errbox:
            err = self._errbox[0]
            self._errbox = []
            raise err
    result = wait


_IN_FLIGHT: list = []  # AsyncSaveHandle s not yet waited on


def _drain_in_flight():
    """A new save waits for prior async writes (reference
    save_state_dict.py:104 waits on its async executor the same way) so two
    saves to one path can't interleave. A PRIOR save's write failure is
    surfaced as a loud warning, not an exception — it must not abort the
    new, unrelated save (the user can still catch it via that handle's own
    ``wait()``)."""
    import warnings

    while _IN_FLIGHT:
        h = _IN_FLIGHT.pop()
        try:
            h.wait()
        except Exception as e:
            warnings.warn(
                f"a previous async checkpoint save to {h._path!r} FAILED: "
                f"{type(e).__name__}: {e} — that checkpoint is incomplete",
                stacklevel=3)


import atexit  # noqa: E402

atexit.register(_drain_in_flight)  # never exit with a write mid-file

_UINT_FOR_WIDTH = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _np_dtype(name):
    """Resolve a dtype string incl. ml_dtypes extended types."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _storable(data):
    """(array-as-native-dtype, true-dtype-string). np.savez only round-trips
    builtin numpy dtypes; view extended dtypes as same-width uints."""
    dt = data.dtype
    if dt.kind in "biufc":  # native numpy types round-trip as-is
        return data, dt.name
    return data.view(_UINT_FOR_WIDTH[dt.itemsize]), dt.name


def _atomic_json(obj, dest, fire_site=None):
    from ...utils.retry import atomic_write

    atomic_write(dest, lambda f: f.write(json.dumps(obj).encode()),
                 fire_site=fire_site)


def _write_commit(path, world_size=1):
    """Publish the COMMIT sentinel — written strictly after every shard and
    metadata fragment of this save is durable. Records the saving world size
    so readers can detect a missing rank's fragment."""
    _atomic_json({"version": 3, "world_size": int(world_size)},
                 os.path.join(path, COMMIT_FILE))


def is_committed(path):
    """True iff ``path`` holds a committed checkpoint (COMMIT present and
    parseable)."""
    return _read_commit(path) is not None


def _read_commit(path):
    try:
        with open(os.path.join(path, COMMIT_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Reference save_state_dict.py:104. With ``async_save=True`` the
    device->host snapshot is taken before returning and the disk write runs
    on a background thread; returns an :class:`AsyncSaveHandle` (sync saves
    return None)."""
    _drain_in_flight()
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    nprocs = jax.process_count()
    if async_save and nprocs > 1:
        # the commit protocol needs collectives (prepare barrier + success
        # allgather) and collectives from a background thread can interleave
        # with main-thread training collectives across processes — downgrade
        # to a synchronous save rather than risk a cross-host hang
        import warnings

        warnings.warn(
            "async_save is downgraded to a synchronous save in "
            "multi-process runs (the commit protocol's collectives must "
            "stay on the main thread)", stacklevel=2)
        async_save = False
    if rank == coordinator_rank:
        # retract the previous save's COMMIT first: while this save is
        # rewriting shards the directory must not read as committed
        commit_p = os.path.join(path, COMMIT_FILE)
        if os.path.exists(commit_p):
            os.remove(commit_p)
        # remove fragments from a previous save with more ranks — they are
        # not overwritten below and _merged_metadata would read stale shards
        import re

        for fn in os.listdir(path):
            m = re.match(r"rank(\d+)\.(npz|meta\.json)$", fn)
            if m and int(m.group(1)) >= nprocs:
                os.remove(os.path.join(path, fn))
    if nprocs > 1:
        # no rank may overwrite shards until the coordinator has retracted
        # the previous COMMIT — otherwise a coordinator killed pre-retract
        # leaves an old COMMIT certifying a mix of old and new shards
        sync_processes(f"ckpt_prepare:{path}")
    fragment = {"state": {}, "version": 3, "rank": rank,
                "world_size": nprocs}
    payload = {}
    for name, t in state_dict.items():
        arr = t._data if isinstance(t, Tensor) else np.asarray(t)
        # single-controller: save unique (replica 0) shards only, reading
        # each shard's device-local buffer directly (no cross-device gather)
        saved = []
        true_dtype = None
        shards = (list(arr.addressable_shards)
                  if isinstance(arr, jax.Array)
                  and hasattr(arr, "addressable_shards") else [])
        if shards and not any(s.replica_id == 0 for s in shards):
            # every addressable shard is a replica of data whose replica-0
            # copy lives on another process (e.g. tp-sharded within hosts,
            # replicated across the host axis): that rank saves it; writing
            # the global array here would need a cross-host gather
            continue
        if shards and any(s.replica_id == 0 for s in shards):
            for i, sh in enumerate(
                    s for s in shards if s.replica_id == 0):
                key = f"{name}@r{rank}s{i}"
                index = [[0 if s.start is None else int(s.start),
                          None if s.stop is None else int(s.stop)]
                         for s in sh.index]
                data, true_dtype = _storable(np.asarray(sh.data))
                payload[key] = data
                saved.append({"key": key, "index": index,
                              "crc32": zlib.crc32(data.tobytes())})
        else:
            key = f"{name}@r{rank}full"
            data, true_dtype = _storable(np.asarray(arr))
            payload[key] = data
            saved.append({"key": key, "index": None,
                          "crc32": zlib.crc32(data.tobytes())})
        fragment["state"][name] = {
            "global_shape": list(np.shape(arr)),
            "dtype": true_dtype,
            "shards": saved,
        }
    def write():
        from ...utils.retry import atomic_write, retry_os

        # payload arrays are host copies (np.asarray above) — training may
        # have moved on; write order is the commit protocol: shards, then
        # metadata fragments, then COMMIT — a reader that sees COMMIT sees
        # everything, and each file lands via tmp+fsync+rename
        err = None
        try:
            retry_os(lambda: atomic_write(
                os.path.join(path, f"rank{rank}.npz"),
                lambda f: np.savez(f, **payload),
                fire_site="ckpt.shard_write"))
            retry_os(lambda: _atomic_json(
                fragment, os.path.join(path, f"rank{rank}.meta.json")))
        except Exception as e:
            err = e  # must still reach the collective below — a rank that
            #          bails early would hang every other rank
        if nprocs > 1:
            # COMMIT certifies EVERY rank's files, so the coordinator may
            # only commit after all ranks report a durable write (Orbax
            # runs the same sync before its commit marker); the allgather
            # doubles as the barrier and carries each rank's success flag.
            # Single-host saves skip the sync entirely.
            all_ok = allgather_success(err is None, f"write:{path}")
        else:
            all_ok = err is None
        if err is not None:
            raise err
        if not all_ok:
            # another rank's write failed: nothing was committed — surface
            # that on every rank instead of returning as if the save landed
            raise CheckpointCorruptionError(
                f"checkpoint save at {path} failed on another process; "
                "COMMIT was not written")
        if rank == coordinator_rank and all_ok:
            # API-parity marker only (the coordinator's own fragment); load
            # always merges rank*.meta.json fragments and never reads this
            retry_os(lambda: _atomic_json(
                fragment, os.path.join(path, "metadata.json")))
            retry_os(lambda: _write_commit(path, world_size=nprocs))

    if not async_save:
        write()
        return None

    errbox = []

    def run():
        try:
            write()
        except BaseException as e:  # surfaced on handle.wait()
            errbox.append(e)

    # non-daemon: interpreter exit joins the thread instead of killing the
    # write mid-file (plus the atexit drain above for belt and braces)
    thread = threading.Thread(target=run, name="ckpt-async-save",
                              daemon=False)
    thread.start()
    handle = AsyncSaveHandle(thread, errbox, path=path)
    _IN_FLIGHT.append(handle)
    return handle


def _merged_metadata(path):
    """Union of every rank's metadata fragment (shard lists concatenated).
    Also records the max fragment ``version`` and the set of fragment ranks
    under private ``_version`` / ``_ranks`` keys for commit verification."""
    merged = {"state": {}, "_version": 1, "_ranks": set()}
    names = sorted(fn for fn in os.listdir(path)
                   if fn.endswith(".meta.json"))
    if not names:
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        if meta.get("version", 1) >= 2:
            # v2+ metadata.json is one rank's fragment, not a merged view —
            # loading from it alone would silently zero other ranks' shards
            raise CheckpointCorruptionError(
                f"checkpoint at {path} is missing its rank*.meta.json "
                "fragments (v2+ layout); copy the full checkpoint directory")
        meta.setdefault("_version", meta.get("version", 1))
        meta.setdefault("_ranks", set())
        return meta
    for fn in names:
        try:
            with open(os.path.join(path, fn)) as f:
                frag = json.load(f)
        except ValueError as e:
            raise CheckpointCorruptionError(
                f"metadata fragment {fn!r} in checkpoint {path} is not "
                f"valid JSON ({e}); the save was torn mid-write") from e
        merged["_version"] = max(merged["_version"],
                                 int(frag.get("version", 1)))
        if "rank" in frag:
            merged["_ranks"].add(int(frag["rank"]))
        for name, info in frag["state"].items():
            if name not in merged["state"]:
                merged["state"][name] = {
                    "global_shape": info["global_shape"],
                    "dtype": info["dtype"],
                    "shards": [],
                }
            merged["state"][name]["shards"].extend(info["shards"])
    return merged


def _check_commit(path, metadata):
    """v3 checkpoints must carry COMMIT, and every fragment rank of the
    saving world must be present — anything less is a torn save."""
    if metadata.get("_version", 1) < 3:
        return  # pre-commit-protocol layout: nothing to verify
    commit = _read_commit(path)
    if commit is None:
        raise CheckpointCorruptionError(
            f"checkpoint at {path} has no COMMIT sentinel — the save was "
            "killed before completing; resume from the newest committed "
            "step (CheckpointManager.latest_valid_step skips this one)")
    world = int(commit.get("world_size", 1))
    missing = set(range(world)) - metadata.get("_ranks", set())
    if missing:
        raise CheckpointCorruptionError(
            f"checkpoint at {path} was saved by {world} processes but the "
            f"metadata fragments of rank(s) {sorted(missing)} are missing")


class _ShardReader:
    """Lazy npz access with typed corruption errors and CRC verification."""

    def __init__(self, path):
        self.path = path
        self._files = []
        for fn in sorted(os.listdir(path)):
            if not fn.endswith(".npz"):
                continue
            try:
                self._files.append(np.load(os.path.join(path, fn)))
            except Exception as e:
                raise CheckpointCorruptionError(
                    f"checkpoint shard file {fn!r} in {path} is unreadable "
                    f"({type(e).__name__}: {e})") from e

    def read(self, shard, dtype):
        key = shard["key"]
        for f in self._files:
            if key not in f:
                continue
            try:
                data = f[key]
            except Exception as e:  # zipfile/zlib CRC or truncation errors
                raise CheckpointCorruptionError(
                    f"shard {key!r} in checkpoint {self.path} is corrupt "
                    f"({type(e).__name__}: {e})") from e
            want = shard.get("crc32")
            if want is not None and zlib.crc32(data.tobytes()) != want:
                raise CheckpointCorruptionError(
                    f"shard {key!r} in checkpoint {self.path} failed CRC32 "
                    "verification — the bytes on disk do not match what "
                    "was saved")
            if data.dtype != dtype:
                data = data.view(dtype)
            return data
        raise CheckpointCorruptionError(
            f"shard {key!r} named by the metadata of checkpoint "
            f"{self.path} is absent from every shard file")

    def close(self):
        for f in self._files:
            try:
                f.close()
            except Exception:
                pass
        self._files = []


def verify_checkpoint(path):
    """Full integrity pass: commit sentinel, fragment completeness, and
    CRC32 of every shard. Returns the merged metadata on success; raises
    :class:`CheckpointCorruptionError` (or ``FileNotFoundError``) on any
    torn/corrupt state."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint directory at {path!r}")
    try:
        metadata = _merged_metadata(path)
    except FileNotFoundError:
        # no sharded payload at all (a pickle/writer-only save): the files
        # are whole by the atomic-rename guarantee, COMMIT alone decides
        if is_committed(path):
            return {"state": {}}
        raise CheckpointCorruptionError(
            f"checkpoint at {path} has neither shard metadata nor a "
            "COMMIT sentinel — nothing verifiable was saved there")
    _check_commit(path, metadata)
    reader = _ShardReader(path)
    try:
        for name, info in metadata["state"].items():
            dtype = _np_dtype(info["dtype"])
            for sh in info["shards"]:
                reader.read(sh, dtype)
    finally:
        reader.close()
    return metadata


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False):
    """Reference load_state_dict.py:365 — fills `state_dict` tensors in
    place, resharding to each tensor's current placement. Verifies the
    commit protocol (COMMIT sentinel + fragment completeness, v3 layouts)
    and each shard's CRC32, raising :class:`CheckpointCorruptionError` on a
    torn or corrupt save instead of returning garbage."""
    if not os.path.isdir(path):
        raise FileNotFoundError(
            f"no checkpoint directory at {path!r}; "
            "CheckpointManager.latest_valid_step() locates the newest "
            "committed step under a checkpoint root")
    metadata = _merged_metadata(path)
    _check_commit(path, metadata)
    reader = _ShardReader(path)
    try:
        for name, t in state_dict.items():
            if name not in metadata["state"]:
                continue
            info = metadata["state"][name]
            dtype = _np_dtype(info["dtype"])
            full = np.zeros(info["global_shape"], dtype=dtype)
            if full.ndim == 0:
                full = np.asarray(reader.read(info["shards"][0], dtype))
            else:
                for sh in info["shards"]:
                    data = reader.read(sh, dtype)
                    if sh["index"] is None:
                        full = np.asarray(data)
                    else:
                        idx = tuple(slice(a, b) for a, b in sh["index"])
                        full[idx] = data
            arr = t._data
            target_sharding = getattr(arr, "sharding", None)
            import jax.numpy as jnp

            new = jnp.asarray(full).astype(arr.dtype)
            if target_sharding is not None and isinstance(
                    target_sharding, jax.sharding.NamedSharding):
                new = jax.device_put(new.reshape(arr.shape), target_sharding)
            t._rebind(new.reshape(arr.shape))
    finally:
        reader.close()
    return state_dict


from .manager import (  # noqa: E402  (needs the fns above)
    CheckpointManager, PlanMismatchError)
