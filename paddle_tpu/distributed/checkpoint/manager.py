"""Checkpoint lifecycle manager: step-stamped saves, retention, auto-resume.

Reference analog: the reference Paddle's fleet checkpoint flow (coordinator-
gathered metadata + elastic auto-restart at the latest save). Here the same
lifecycle is a single object over the v3 commit-protocol layout written by
:func:`paddle_tpu.distributed.save_state_dict`:

    root/
      step_100/   rank0.npz  rank0.meta.json  metadata.json  COMMIT
                  optimizer.pdopt  scaler.pdscaler
      step_200/   ...                                   <- newest committed

- ``save(step, model=…, optimizer=…, scaler=…)`` writes the auxiliary
  pickles first (atomic, via ``paddle.save``) and the model shards +
  ``COMMIT`` last, so the sentinel certifies the whole directory.
- ``latest_valid_step()`` is the crash-recovery query: the newest step whose
  directory is committed (optionally CRC-verified), skipping torn saves.
- ``auto_resume(model, optimizer, scaler)`` restores all three from that
  step (the optimizer's global step rides in its own state dict) and
  returns the step number, or ``None`` when nothing valid exists.
- Retention keeps the last ``keep_last_n`` committed steps and never
  deletes the newest committed one; with ``async_save`` it is deferred
  until the in-flight :class:`AsyncSaveHandle` lands (the next ``save`` or
  an explicit ``wait`` drains it), so a checkpoint is never pruned while
  its successor is still being written.
- **Health metadata** (the divergence-sentinel contract): a committed step
  is *healthy* only once ``k`` clean metric-fetch windows have passed
  beyond it (``note_window(clean, k)`` — the sentinel calls it at every
  window boundary; a bad window resets every pending count, so a
  checkpoint written during an undetected spike can never become a
  rollback target). ``tag_healthy`` stamps a ``HEALTHY`` marker into the
  step dir, ``latest_healthy_step()`` is the rollback query, retention
  never deletes the newest healthy step, and ``drop_steps_after(step)``
  is the post-rollback sweep of poisoned newer checkpoints.
"""

from __future__ import annotations

import os
import re
import shutil
import time

import jax

from ...framework import io as _fio
from ...observability import metrics as _obs_metrics
from ...observability import trace as _obs_trace
from . import (_write_commit, is_committed, load_state_dict, save_state_dict,
               verify_checkpoint)
from ...framework.io import CheckpointCorruptionError

__all__ = ["CheckpointManager", "PlanMismatchError"]

_STEP_RE = re.compile(r"^step_(\d+)$")

# checkpoint IO observability (ISSUE 10): durations as histograms, bytes
# as a counter — unlabeled (process-wide; a process rarely runs more than
# one manager, and root paths are unbounded strings the label-cardinality
# rule forbids). Checkpoint IO is already a host-blocking region, so the
# spans/timers sit at an allowed sync point by construction. For async
# saves the duration covers submission; the shard-write tail is the
# AsyncSaveHandle's, and bytes are accounted when wait() lands it.
_H_SAVE_S = _obs_metrics.histogram(
    "ckpt_save_seconds", "wall time of CheckpointManager.save (async: "
    "the synchronous submission portion)",
    buckets=_obs_metrics.DEFAULT_SECONDS_BUCKETS)
_H_RESTORE_S = _obs_metrics.histogram(
    "ckpt_restore_seconds", "wall time of CheckpointManager.auto_resume "
    "when a checkpoint was actually restored",
    buckets=_obs_metrics.DEFAULT_SECONDS_BUCKETS)
_M_SAVE_BYTES = _obs_metrics.counter(
    "ckpt_save_bytes_total", "bytes in committed checkpoint step dirs, "
    "accounted when the save lands")


def _dir_bytes(path):
    total = 0
    try:
        for root, _dirs, files in os.walk(path):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(root, fn))
                except OSError:
                    pass
    except OSError:
        pass
    return total
_OPT_FILE = "optimizer.pdopt"
_SCALER_FILE = "scaler.pdscaler"
_SAMPLER_FILE = "sampler.pdsampler"
# rank-local data-stream cursors (sharded streaming ingestion): one file
# per rank beside the coordinator's legacy single-cursor file
_RANK_SAMPLER_RE = re.compile(r"^sampler\.rank(\d+)\.pdsampler$")


def _rank_sampler_file(rank):
    return f"sampler.rank{int(rank)}.pdsampler"
_HEALTH_FILE = "HEALTHY"
_PLAN_FILE = "plan.json"


class PlanMismatchError(RuntimeError):
    """A checkpoint written under one sharding plan is being restored
    under an incompatible one (different mesh shape, or different
    param-spec/strategy tables over the same mesh). Restoring anyway
    would mis-shard silently — weights land on a layout the compiled
    step was not built for. Re-create the Plan the checkpoint records
    (``plan.json`` in the step directory holds its mesh + digest), or
    restore with ``plan=None`` to skip the check deliberately."""


def _resolve_sampler(obj):
    """Accept a BucketedBatchSampler, a DataLoader, or a DevicePrefetcher
    as ``sampler=`` — whatever layer of the input pipeline the caller
    holds — and unwrap to the object owning the resumable stream state."""
    from ...io import resolve_resumable

    r = resolve_resumable(obj)
    if r is None:
        raise TypeError(
            f"{type(obj).__name__} is not a resumable data stream: it "
            "must expose (or wrap something exposing) state_dict/"
            "set_state_dict/advance — see io.BucketedBatchSampler")
    return r


class CheckpointManager:
    def __init__(self, root, keep_last_n=None, async_save=False):
        if keep_last_n is not None and int(keep_last_n) < 1:
            raise ValueError("keep_last_n must be >= 1 (the newest committed "
                             "checkpoint is never deleted)")
        self.root = str(root)
        self.keep_last_n = None if keep_last_n is None else int(keep_last_n)
        self.async_save = bool(async_save)
        self._pending = None  # in-flight (step, AsyncSaveHandle)
        # health tagging (divergence sentinel): committed steps awaiting
        # their k clean windows, {step: clean_windows_seen_since_commit}.
        # In-memory on purpose — a crash loses pending counts and the
        # restarted process re-earns them, which is conservative (a step
        # is never tagged healthy on less evidence than k clean windows
        # observed by ONE process lifetime)
        self._health_pending: dict[int, int] = {}
        os.makedirs(self.root, exist_ok=True)

    # ---- layout ---------------------------------------------------------
    def step_dir(self, step):
        return os.path.join(self.root, f"step_{int(step)}")

    def steps(self):
        """All step-stamped directories under the root, sorted ascending
        (committed or not)."""
        out = []
        for entry in os.listdir(self.root):
            m = _STEP_RE.match(entry)
            if m and os.path.isdir(os.path.join(self.root, entry)):
                out.append(int(m.group(1)))
        return sorted(out)

    def committed_steps(self):
        return [s for s in self.steps() if is_committed(self.step_dir(s))]

    def _recover_quarantines(self):
        """A crash mid-resave leaves the only committed copy of a step
        under ``step_{n}.replaced.*`` while ``step_{n}`` itself is torn —
        restore it so resume finds it. Coordinator-only (shared fs)."""
        if jax.process_index() != 0 or self._pending is not None:
            return
        for entry in os.listdir(self.root):
            base, sep, _ = entry.partition(".replaced.")
            if not sep or not _STEP_RE.match(base):
                continue
            q = os.path.join(self.root, entry)
            d = os.path.join(self.root, base)
            if not os.path.isdir(q) or not is_committed(q):
                continue
            if is_committed(d):
                continue  # the resave landed; retention sweeps the copy
            if os.path.isdir(d):
                shutil.rmtree(d)  # the torn resave attempt
            os.rename(q, d)

    def latest_valid_step(self, verify=False):
        """Newest step whose directory is committed; ``verify=True`` also
        CRC-checks every shard, walking further back past corrupt saves.
        Restores a quarantined committed copy of a step whose re-save was
        torn by a crash. Returns ``None`` when no valid checkpoint
        exists."""
        self._recover_quarantines()
        for s in reversed(self.committed_steps()):
            if not verify:
                return s
            try:
                verify_checkpoint(self.step_dir(s))
                return s
            except (CheckpointCorruptionError, FileNotFoundError):
                continue
        return None

    # ---- health metadata (divergence sentinel) --------------------------
    def is_healthy(self, step):
        """A step is healthy when it is committed AND carries the HEALTHY
        tag — i.e. the sentinel saw ``k`` clean windows pass beyond it."""
        d = self.step_dir(step)
        return (is_committed(d)
                and os.path.exists(os.path.join(d, _HEALTH_FILE)))

    def tag_healthy(self, step):
        """Stamp a committed step as a valid rollback target (atomic
        marker write; coordinator-only on multi-process filesystems).
        No-op on an uncommitted/missing step — health can never certify
        data the commit protocol has not."""
        if jax.process_index() != 0:
            return False
        d = self.step_dir(step)
        if not is_committed(d):
            return False
        marker = os.path.join(d, _HEALTH_FILE)
        tmp = f"{marker}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write("healthy\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, marker)
        return True

    def note_window(self, clean, k=1):
        """Sentinel hook, called once per metric-fetch window boundary
        (after any checkpoint written at that boundary): a **clean**
        window first credits every pending committed step — promoting
        those that reach ``k`` clean windows to HEALTHY — and then
        registers newly committed steps at zero credits (so the step
        saved *at this very boundary* still needs ``k`` MORE clean
        windows). A **bad** window resets every pending count to zero:
        health requires k *consecutive* clean windows beyond the step.
        Returns the list of steps promoted this call."""
        promoted = []
        if not clean:
            for s in self._health_pending:
                self._health_pending[s] = 0
            return promoted
        k = max(1, int(k))
        for s in sorted(self._health_pending):
            self._health_pending[s] += 1
            if self._health_pending[s] >= k:
                if self.tag_healthy(s):
                    promoted.append(s)
                self._health_pending.pop(s)
        for s in self.committed_steps():
            if s not in self._health_pending and not self.is_healthy(s):
                self._health_pending[s] = 0
        return promoted

    def latest_healthy_step(self, verify=False):
        """Newest committed step tagged HEALTHY (``verify=True`` also
        CRC-walks it, skipping corrupt ones) — the rollback target query.
        ``None`` when no healthy checkpoint exists yet."""
        self._recover_quarantines()
        for s in reversed(self.committed_steps()):
            if not self.is_healthy(s):
                continue
            if not verify:
                return s
            try:
                verify_checkpoint(self.step_dir(s))
                return s
            except (CheckpointCorruptionError, FileNotFoundError):
                continue
        return None

    def drop_steps_after(self, step):
        """Post-rollback sweep: delete every step directory (committed or
        torn) NEWER than ``step`` — they were written past the divergence
        point and hold poisoned states that must never win a
        ``latest_valid_step`` race against the healthy restore point.
        Their quarantine copies go too. Coordinator-only; returns the
        dropped step numbers."""
        self.wait()
        step = int(step)
        dropped = [s for s in self.steps() if s > step]
        for s in list(self._health_pending):
            if s > step:
                self._health_pending.pop(s)
        if jax.process_index() != 0:
            return dropped
        for s in dropped:
            shutil.rmtree(self.step_dir(s), ignore_errors=True)
        for entry in os.listdir(self.root):
            base, sep, _ = entry.partition(".replaced.")
            m = _STEP_RE.match(base)
            if sep and m and int(m.group(1)) > step:
                shutil.rmtree(os.path.join(self.root, entry),
                              ignore_errors=True)
        return dropped

    # ---- save -----------------------------------------------------------
    # ---- plan fingerprint ----------------------------------------------
    def plan_fingerprint(self, step):
        """The ``{"mesh": {...}, "digest": ...}`` fingerprint recorded at
        save time, or ``None`` for plan-less / pre-plan checkpoints."""
        import json

        p = os.path.join(self.step_dir(step), _PLAN_FILE)
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    @staticmethod
    def _check_plan(recorded, plan, step):
        """Raise :class:`PlanMismatchError` when ``plan``'s fingerprint
        disagrees with the recorded one. A plan-less checkpoint restored
        under a plan (or vice versa) passes — there is nothing recorded
        to contradict; the layout commit in ``auto_resume``'s callers
        (``FusedTrainStep._adopt_external_rebinds``) re-places arrays."""
        if recorded is None or plan is None:
            return
        fp = plan.fingerprint()
        if dict(recorded.get("mesh", {})) != dict(fp["mesh"]):
            raise PlanMismatchError(
                f"checkpoint step_{step} was written under mesh "
                f"{recorded.get('mesh')} but is being restored under mesh "
                f"{fp['mesh']} — restoring would mis-shard silently; "
                "rebuild the recorded mesh (README: multichip recipe) or "
                "pass plan=None to override")
        if recorded.get("digest") != fp["digest"]:
            raise PlanMismatchError(
                f"checkpoint step_{step} was written under the same mesh "
                f"{fp['mesh']} but a DIFFERENT plan table (digest "
                f"{recorded.get('digest')} vs {fp['digest']}): param/"
                "moment layouts differ — rebuild the recorded plan or "
                "pass plan=None to override")

    def save(self, step, model=None, optimizer=None, scaler=None,
             state_dict=None, writer=None, async_save=None, sampler=None,
             plan=None):
        """Write a committed checkpoint for ``step``. ``model`` /
        ``state_dict`` go through the sharded writer (COMMIT last);
        ``optimizer`` / ``scaler`` / ``sampler`` state dicts are pickled
        atomically before the shards (``sampler`` accepts the batch
        sampler, its DataLoader, or a DevicePrefetcher — the resumable
        data-stream cursor is persisted so a restart replays the exact
        remaining batch sequence); ``writer(dir_path)`` lets callers drop
        extra files into the directory under the same commit (hapi's
        ModelCheckpoint uses this). Returns the :class:`AsyncSaveHandle`
        for async saves, else ``None``."""
        # land the PREVIOUS async save before starting this save's timer:
        # its write tail (handle.wait + bytes walk + retention) belongs to
        # that save, not to this one's ckpt_save_seconds observation
        self.wait()
        t0_ns = time.perf_counter_ns()
        try:
            handle = self._save_impl(step, model=model, optimizer=optimizer,
                                     scaler=scaler, state_dict=state_dict,
                                     writer=writer, async_save=async_save,
                                     sampler=sampler, plan=plan)
        finally:
            t1_ns = time.perf_counter_ns()
            _H_SAVE_S.observe((t1_ns - t0_ns) / 1e9)
            _obs_trace.add_complete("ckpt.save", t0_ns, t1_ns, cat="ckpt",
                                    args={"step": int(step)})
        if handle is None:
            # synchronous save: the directory just committed — account it
            _M_SAVE_BYTES.inc(_dir_bytes(self.step_dir(step)))
        return handle

    def _save_impl(self, step, model=None, optimizer=None, scaler=None,
                   state_dict=None, writer=None, async_save=None,
                   sampler=None, plan=None):
        self.wait()  # land the previous async write + run its retention
        if async_save is None:
            async_save = self.async_save
        # one snapshot serves the legacy file and this rank's cursor
        # file: state_dict() is not assumed cheap or pure, and two calls
        # could yield two diverging files under concurrent consumption
        sampler_state = (None if sampler is None
                         else _resolve_sampler(sampler).state_dict())
        d = self.step_dir(step)
        # directory lifecycle (quarantine / cleanup / aux pickles) is
        # coordinator-only: in a multi-process save every rank enters here,
        # and racing renames/rmtrees would corrupt the very directory the
        # shard writes are about to target
        if jax.process_index() == 0:
            if os.path.isdir(d):
                if is_committed(d):
                    # never destroy committed data before its replacement
                    # commits: quarantine it out of the step_{n} namespace
                    # (atomic rename); retention sweeps it once the new
                    # save lands, and a crash mid-resave leaves it
                    # recoverable via _recover_quarantines
                    os.rename(d, f"{d}.replaced.{os.getpid()}")
                else:
                    shutil.rmtree(d)  # torn attempt at the same step
            os.makedirs(d, exist_ok=True)
            if optimizer is not None:
                _fio.save(optimizer.state_dict(), os.path.join(d, _OPT_FILE))
            if scaler is not None:
                _fio.save(scaler.state_dict(),
                          os.path.join(d, _SCALER_FILE))
            if sampler_state is not None:
                _fio.save(sampler_state, os.path.join(d, _SAMPLER_FILE))
            if plan is not None:
                # step metadata: mesh shape + rule/strategy digest, so a
                # restore onto an incompatible mesh fails typed instead
                # of mis-sharding silently (auto_resume(plan=...))
                import json

                from ...utils.retry import atomic_write

                payload = json.dumps(plan.fingerprint()).encode()
                atomic_write(os.path.join(d, _PLAN_FILE),
                             lambda f: f.write(payload))
            if writer is not None:
                writer(d)
        if jax.process_count() > 1:
            # other ranks must not start shard writes into a directory the
            # coordinator is still quarantining/cleaning
            from . import sync_processes

            sync_processes(f"ckpt_mgr_prepare:{d}")
            os.makedirs(d, exist_ok=True)  # non-shared-fs local mkdir
        if sampler_state is not None:
            # rank-LOCAL stream cursors (ISSUE 13): a sharded-by-rank
            # StreamingDataset has a different position per rank, so the
            # coordinator's sampler.pdsampler (kept above for back-compat
            # and single-cursor samplers) is not enough — every rank
            # writes its own sampler.rank{i}.pdsampler (its own file: no
            # write races), before the shard-write commit barrier so
            # COMMIT still implies all of them landed. Written in
            # single-process runs too: a world-1 checkpoint must stay
            # resumable into a LARGER world (auto_resume hands the rank
            # states to set_group_state, which re-balances). The file is
            # named from the STATE's own rank when it has one (under
            # coordination-free launches, PADDLE_SKIP_DIST_INIT, every
            # worker is jax process 0 regardless of its data rank).
            # NOTE the supported topologies: multi-rank managers over
            # ONE root require the coordination service (the barriers
            # above serialize the directory lifecycle); coordination-
            # free workers must each own a PRIVATE root (the chaos
            # stream drill's ckpt.rank{i} pattern) — two uncoordinated
            # saves into one root would race the quarantine/commit
            # lifecycle no matter how the cursor files are named.
            rank = sampler_state.get("rank", jax.process_index()) \
                if isinstance(sampler_state, dict) else \
                jax.process_index()
            _fio.save(sampler_state,
                      os.path.join(d, _rank_sampler_file(rank)))
        sd = {}
        if model is not None:
            sd.update(model.state_dict())
        if state_dict:
            sd.update(state_dict)
        if sd:
            handle = save_state_dict(sd, d, async_save=async_save)
            if handle is not None:
                self._pending = (int(step), handle)
                return handle
        else:
            _write_commit(d)  # pickle/writer-only save: commit it here
        self._retain()
        return None

    def wait(self):
        """Block until the in-flight async save lands (re-raising its write
        failure), then run the retention it deferred."""
        if self._pending is None:
            return
        _step, handle = self._pending
        self._pending = None
        handle.wait()
        _M_SAVE_BYTES.inc(_dir_bytes(self.step_dir(_step)))
        self._retain()

    def _retain(self):
        """keep-last-N over committed steps; runs only right after a save
        lands (never with a write in flight) and only on the coordinator.
        Uncommitted (torn) directories are garbage and are swept too, as
        are ``*.replaced.*`` quarantines — those only once their re-save
        landed, or once retention is enabled and a newer commit exists
        (which is always true here). Quarantines that are NOT redundant
        (they hold the only committed copy of the newest step, accumulated
        by repeated torn re-saves) are kept by default and bounded by
        ``FLAGS_ckpt_quarantine_keep`` when set >= 0. The newest committed
        step always survives, and so does the newest HEALTHY step — the
        divergence sentinel's only rollback target must outlive any number
        of newer (possibly poisoned) saves."""
        from ...core.flags import flag_value

        if jax.process_index() != 0:
            return
        committed = self.committed_steps()
        newest = committed[-1] if committed else None
        survivors = []  # non-redundant quarantines (see below)
        for entry in os.listdir(self.root):
            base, sep, _ = entry.partition(".replaced.")
            m = _STEP_RE.match(base)
            if not sep or not m:
                continue
            # a quarantine is prunable only once it is redundant: its
            # re-save landed, or a newer committed step supersedes it —
            # never while it holds the only committed copy of its step
            if is_committed(os.path.join(self.root, base)) or (
                    newest is not None and newest > int(m.group(1))):
                shutil.rmtree(os.path.join(self.root, entry),
                              ignore_errors=True)
            else:
                survivors.append(entry)
        # flag-gated bound on the non-redundant quarantines (PR-2 said
        # "never delete"; a crash-loop re-saving the same newest step can
        # still grow them without bound — the flag opts into keeping only
        # the newest N, default -1 keeps all)
        qkeep = int(flag_value("ckpt_quarantine_keep", -1))
        if qkeep >= 0 and len(survivors) > qkeep:
            def qage(entry):
                try:
                    return os.path.getmtime(os.path.join(self.root, entry))
                except OSError:
                    return 0.0
            survivors.sort(key=qage, reverse=True)  # newest first
            for entry in survivors[qkeep:]:
                shutil.rmtree(os.path.join(self.root, entry),
                              ignore_errors=True)
        if self.keep_last_n is None:
            return
        healthy = [s for s in committed if self.is_healthy(s)]
        newest_healthy = healthy[-1] if healthy else None
        victims = [s for s in self.steps() if s not in committed]
        keep = max(1, self.keep_last_n)
        victims += [s for s in committed[:-keep] if s != newest_healthy]
        for s in victims:
            shutil.rmtree(self.step_dir(s), ignore_errors=True)

    # ---- resume ---------------------------------------------------------
    def auto_resume(self, model=None, optimizer=None, scaler=None,
                    verify=False, sampler=None, step=None, plan=None):
        """Restore ``model`` + ``optimizer`` + ``scaler`` + ``sampler``
        from the newest valid checkpoint and return its step (the
        optimizer's global step / LR schedule ride in its state dict; the
        scaler's loss-scale schedule in its own; the sampler's epoch +
        consumed-batch cursor + shuffle seed in ``sampler.pdsampler`` —
        restoring it makes the restart replay the *exact* remaining batch
        sequence). Returns ``None`` — touching nothing — when no committed
        checkpoint exists, so cold starts and warm restarts share one call.
        ``verify=True`` CRC-walks candidate steps before loading (load
        itself re-verifies what it reads — the deep pre-pass costs a second
        read of the chosen checkpoint and is for resuming past bit-rot).
        ``step=`` pins the restore to that exact committed step instead of
        the newest — the divergence-rollback path restores the
        ``latest_healthy_step()`` this way, deliberately skipping newer
        (poisoned) saves; an uncommitted ``step`` raises ValueError."""
        self.wait()
        if step is None:
            step = self.latest_valid_step(verify=verify)
        else:
            step = int(step)
            self._recover_quarantines()
            if not is_committed(self.step_dir(step)):
                raise ValueError(
                    f"auto_resume(step={step}): no committed checkpoint "
                    f"at {self.step_dir(step)} (committed steps: "
                    f"{self.committed_steps()})")
            if verify:
                verify_checkpoint(self.step_dir(step))
        if step is None:
            return None
        # plan fingerprint gate BEFORE any state is touched: a mismatch
        # must leave model/optimizer exactly as they were
        self._check_plan(self.plan_fingerprint(step), plan, step)
        t0_ns = time.perf_counter_ns()
        d = self.step_dir(step)
        if model is not None and any(
                fn.endswith(".npz") for fn in os.listdir(d)):
            load_state_dict(model.state_dict(), d)
        opt_p = os.path.join(d, _OPT_FILE)
        if optimizer is not None and os.path.exists(opt_p):
            optimizer.set_state_dict(_fio.load(opt_p))
        sc_p = os.path.join(d, _SCALER_FILE)
        if scaler is not None and os.path.exists(sc_p):
            scaler.load_state_dict(_fio.load(sc_p))
        if sampler is not None:
            self._restore_sampler(sampler, d)
        t1_ns = time.perf_counter_ns()
        _H_RESTORE_S.observe((t1_ns - t0_ns) / 1e9)
        _obs_trace.add_complete("ckpt.restore", t0_ns, t1_ns, cat="ckpt",
                                args={"step": int(step)})
        return step

    def _restore_sampler(self, sampler, d):
        """Restore the data-stream cursor(s) recorded in step dir ``d``.

        Precedence: per-rank cursor files (``sampler.rank{i}.pdsampler``,
        written by multi-process saves) beat the coordinator's legacy
        single file. A resumable that understands group state (a
        sharded-by-rank ``StreamingDataset``) receives EVERY rank's
        state via ``set_group_state`` — that is what lets an elastic
        restart under a different world size re-balance the unconsumed
        shards while preserving in-progress cursors; everything else
        restores its own rank's file (same-world restarts), falling back
        to the legacy file."""
        r = _resolve_sampler(sampler)
        rank_states = {}
        for fn in os.listdir(d):
            m = _RANK_SAMPLER_RE.match(fn)
            if m:
                rank_states[int(m.group(1))] = os.path.join(d, fn)
        if rank_states and hasattr(r, "set_group_state"):
            r.set_group_state([_fio.load(rank_states[k])
                               for k in sorted(rank_states)])
            return
        mine = rank_states.get(jax.process_index())
        if mine is not None:
            r.set_state_dict(_fio.load(mine))
            return
        sp_p = os.path.join(d, _SAMPLER_FILE)
        if os.path.exists(sp_p):
            r.set_state_dict(_fio.load(sp_p))
