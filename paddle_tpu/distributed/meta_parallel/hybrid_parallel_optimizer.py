"""HybridParallelOptimizer + cross-group grad clip.

Reference: fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py
— HybridParallelOptimizer (:254) and HybridParallelClipGrad (:44, global-norm
across tp/pp/sharding groups via allreduce of the local norm squares).

TPU-native: gradients live as global (possibly sharded) arrays, so the global
norm is already global — HybridParallelClipGrad degenerates to
ClipGradByGlobalNorm over the full grad set, which is exactly what the
reference's cross-group allreduce dance computes.
"""

from __future__ import annotations

from ...nn.clip import ClipGradByGlobalNorm

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad(ClipGradByGlobalNorm):
    def __init__(self, clip, hcg):
        clip_norm = getattr(clip, "clip_norm", 1.0)
        super().__init__(clip_norm)
        self._hcg = hcg


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if optimizer._grad_clip is not None and hcg is not None:
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *args, **kwargs):
        self._inner_opt.clear_grad(*args, **kwargs)

    clear_gradients = clear_grad

    def minimize(self, loss, **kwargs):
        return self._inner_opt.minimize(loss, **kwargs)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
