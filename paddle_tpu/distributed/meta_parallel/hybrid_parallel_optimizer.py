"""HybridParallelOptimizer + cross-group grad clip.

Reference: fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py
— HybridParallelOptimizer (:254) and HybridParallelClipGrad (:44, global-norm
across tp/pp/sharding groups via allreduce of the local norm squares).

TPU-native: gradients live as global (possibly sharded) arrays, so the global
norm is already global — HybridParallelClipGrad degenerates to
ClipGradByGlobalNorm over the full grad set, which is exactly what the
reference's cross-group allreduce dance computes.
"""

from __future__ import annotations

from ...nn.clip import ClipGradByGlobalNorm

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad(ClipGradByGlobalNorm):
    def __init__(self, clip, hcg):
        clip_norm = getattr(clip, "clip_norm", 1.0)
        super().__init__(clip_norm)
        self._hcg = hcg


class HybridParallelOptimizer:
    """Consumes the DistributedStrategy toggles that are meaningful on TPU:

    - ``gradient_merge``: accumulate ``k_steps`` micro-steps of gradients
      before the inner optimizer applies (grads accumulate in ``.grad`` by
      construction; the wrapper just defers/averages the apply) — the
      dygraph analog of the reference's gradient_merge meta-optimizer.
    - ``dgc``: the inner Momentum optimizer is swapped for
      ``fleet.meta_optimizers.DGCMomentumOptimizer`` — real top-k
      sparsification with error feedback (matching the reference's
      dgc_optimizer.py wrapping rule: DGC applies to Momentum only).
    - ``localsgd``: divergent per-replica parameters don't exist in the
      eager SPMD path (parameters are one logical array); the real
      implementation is the compiled ``fleet.meta_optimizers.LocalSGD``
      stepper — point the user there instead of silently ignoring.
    - ``a_sync``: async PS training; on TPU the PS analog
      (``distributed.ps.SparseEmbedding``) is synchronous by construction —
      warn.
    """

    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._gm_steps = 0
        self._gm_k = 1
        if strategy is not None:
            if getattr(strategy, "gradient_merge", False):
                cfg = getattr(strategy, "gradient_merge_configs", {})
                self._gm_k = int(cfg.get("k_steps", 1))
                self._gm_avg = bool(cfg.get("avg", True))
            import warnings

            if getattr(strategy, "dgc", False):
                from ...optimizer.optimizers import Momentum
                if isinstance(optimizer, Momentum):
                    from ..fleet.meta_optimizers import DGCMomentumOptimizer
                    cfg = getattr(strategy, "dgc_configs",
                                  {}) or {}
                    dgc = DGCMomentumOptimizer(
                        learning_rate=optimizer._learning_rate,
                        momentum=optimizer._momentum,
                        parameters=optimizer._parameter_list,
                        rampup_begin_step=cfg.get("rampup_begin_step", 0),
                        rampup_step=cfg.get("rampup_step", 1),
                        sparsity=cfg.get("sparsity", (0.999,)),
                        use_nesterov=optimizer._use_nesterov,
                        weight_decay=optimizer.regularization,
                        grad_clip=optimizer._grad_clip)
                    self._inner_opt = optimizer = dgc
                else:
                    warnings.warn(
                        "DistributedStrategy.dgc applies to Momentum only "
                        "(reference dgc_optimizer.py same rule) — ignored",
                        stacklevel=3)
            if getattr(strategy, "localsgd", False):
                warnings.warn(
                    "DistributedStrategy.localsgd: the eager SPMD path has "
                    "one logical parameter copy, so per-replica local steps "
                    "don't arise here; use paddle.distributed.fleet."
                    "meta_optimizers.LocalSGD.from_strategy(strategy, mesh) "
                    "(consumes localsgd_configs) for real LocalSGD "
                    "semantics", stacklevel=3)
            if getattr(strategy, "a_sync", False):
                warnings.warn(
                    "DistributedStrategy.a_sync targets async parameter "
                    "servers; the TPU PS analog is synchronous — ignored",
                    stacklevel=3)
        # Only ClipGradByGlobalNorm needs the cross-group treatment; ByNorm
        # and ByValue are per-tensor-local math that is identical under any
        # sharding, so they pass through untouched (reference
        # hybrid_parallel_optimizer.py:254 wraps only ClipGradByGlobalNorm
        # and warns for the rest).
        if optimizer._grad_clip is not None and hcg is not None:
            if isinstance(optimizer._grad_clip, ClipGradByGlobalNorm):
                optimizer._grad_clip = HybridParallelClipGrad(
                    optimizer._grad_clip, hcg)
            else:
                import warnings

                warnings.warn(
                    f"{type(optimizer._grad_clip).__name__} is per-tensor "
                    "math and needs no hybrid-parallel treatment; it is "
                    "applied as-is (only ClipGradByGlobalNorm is wrapped "
                    "into the cross-group global norm)", stacklevel=3)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        if self._gm_k > 1:
            self._gm_steps += 1
            if self._gm_steps % self._gm_k != 0:
                return  # keep accumulating into .grad
            if self._gm_avg:
                for p in self._inner_opt._parameter_list:
                    if p.grad is not None:
                        p.grad._rebind(p.grad._data / self._gm_k)
        self._inner_opt.step()

    def clear_grad(self, *args, **kwargs):
        # mid-accumulation clears would destroy the merged grads the next
        # micro-steps build on — no-op until the boundary step applied
        if self._gm_k > 1 and self._gm_steps % self._gm_k != 0:
            return
        self._inner_opt.clear_grad(*args, **kwargs)

    clear_gradients = clear_grad

    def minimize(self, loss, **kwargs):
        return self._inner_opt.minimize(loss, **kwargs)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
