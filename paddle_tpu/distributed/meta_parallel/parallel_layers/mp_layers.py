"""Megatron-style tensor-parallel layers.

Reference: fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding (:47),
ColumnParallelLinear (:333), RowParallelLinear (:540), ParallelCrossEntropy
(:741), built on collective PyLayers (mp_ops.py:27-364: c_identity/c_concat/
mp_allreduce autograd pairs).

TPU-native redesign (SURVEY.md §7.1): parameters keep their FULL logical shape
and carry a NamedSharding over the 'mp' mesh axis — GSPMD partitions the
matmuls and inserts the identity/allreduce pairs the reference hand-writes as
PyLayers. ``gather_output=False`` / ``input_is_parallel=True`` become sharding
constraints on activations. On a 1-wide mp axis everything degrades to the
plain layer.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn.initializer import Constant, Normal, XavierUniform
from ....nn.layer.layers import Layer

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _hcg():
    from ...fleet.fleet import fleet_singleton

    try:
        return fleet_singleton.get_hybrid_communicate_group()
    except Exception:
        return None


def _mp_info():
    hcg = _hcg()
    if hcg is None:
        return None, 1
    return hcg.mesh, hcg.get_model_parallel_world_size()


def _shard_param(param, spec):
    """Annotate a parameter with a NamedSharding over the hybrid mesh."""
    mesh, mp = _mp_info()
    if mesh is None or mp <= 1:
        return param
    ok = all(s is None or param._data.shape[i] % mesh.shape[s] == 0
             for i, s in enumerate(spec))
    if not ok:
        return param
    sharding = NamedSharding(mesh, P(*spec))
    param._data = jax.device_put(param._data, sharding)
    param._placement = (mesh, spec)
    return param


def _constrain(t, spec):
    """Sharding constraint on an activation (traced only)."""
    mesh, mp = _mp_info()
    if mesh is None or mp <= 1:
        return t
    if isinstance(t._data, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(
            t._data, NamedSharding(mesh, P(*spec)))
        out = Tensor._wrap(arr)
        out.stop_gradient = t.stop_gradient
        out._node, out._out_idx = t._node, t._out_idx
        return out
    return t


class VocabParallelEmbedding(Layer):
    """reference mp_layers.py:47 — embedding table sharded along vocab dim.
    The lookup is a plain gather with the table sharded on dim 0: GSPMD
    compiles it to the reference's masked local gather + allreduce
    (mp_layers.py:108-120 does this by hand); verified against compiled HLO
    in tests/test_distributed.py (no table all-gather, an all-reduce on the
    activations)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        self.is_mp = _mp_info()[1] > 1
        _shard_param(self.weight, ("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        if self.is_mp:
            # constrain the activations mp-replicated (batch dims left
            # unconstrained so dp/sep sharding flows through): this pins
            # GSPMD to the masked-gather + allreduce strategy and forbids
            # all-gathering the [V, D] table
            spec = (P.UNCONSTRAINED,) * (out.ndim - 1) + (None,)
            out = _constrain(out, spec)
        return out


class ColumnParallelLinear(Layer):
    """reference mp_layers.py:333. Weight [in, out] sharded on out ('mp');
    gather_output=True constrains the output replicated (all_gather),
    False leaves it mp-sharded for a following RowParallelLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if has_bias else None)
        _shard_param(self.weight, (None, "mp"))
        if self.bias is not None:
            _shard_param(self.bias, ("mp",))

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            spec = (None,) * (out.ndim - 1) + (None,)
            return _constrain(out, spec)
        spec = (None,) * (out.ndim - 1) + ("mp",)
        return _constrain(out, spec)


class RowParallelLinear(Layer):
    """reference mp_layers.py:540. Weight [in, out] sharded on in ('mp');
    contracting a mp-sharded dim makes GSPMD insert the allreduce the
    reference codes as mp_allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if has_bias else None)
        _shard_param(self.weight, ("mp", None))

    def forward(self, x):
        if self.input_is_parallel:
            spec = (None,) * (x.ndim - 1) + ("mp",)
            x = _constrain(x, spec)
        out = F.linear(x, self.weight, self.bias)
        return _constrain(out, (None,) * out.ndim)


class ParallelCrossEntropy(Layer):
    """reference mp_layers.py:741 (c_softmax_with_cross_entropy over the
    vocab-sharded logits). GSPMD computes the sharded logsumexp reduction
    automatically; the layer keeps the API."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        return loss.unsqueeze(-1) if loss.ndim < label.ndim + 1 else loss
