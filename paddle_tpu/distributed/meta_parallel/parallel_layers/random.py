"""Hybrid-parallel RNG state tracker.

Reference: fleet/meta_parallel/parallel_layers/random.py
(get_rng_state_tracker, model_parallel_rng contexts for dropout determinism
across TP ranks). Here each named state is a separate Generator seed; in the
GSPMD world tensor-parallel dropout determinism comes from the single global
program, so the tracker mainly preserves the API + seed isolation.
"""

from __future__ import annotations

import contextlib

from ....core import rng as rng_mod

__all__ = ["RNGStatesTracker", "get_rng_state_tracker", "model_parallel_random_seed",
           "MODEL_PARALLEL_RNG"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        gen = rng_mod.Generator(seed)
        self.states_[name] = gen

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        gen = self.states_[name]
        orig = rng_mod.DEFAULT_GENERATOR
        rng_mod.DEFAULT_GENERATOR = gen
        try:
            yield
        finally:
            rng_mod.DEFAULT_GENERATOR = orig


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random

    from ... import get_rank

    seed = seed or (100 + get_rank())
    global_seed = seed
    local_seed = seed + 1024
    _RNG_STATE_TRACKER.reset()
    rng_mod.seed(global_seed)
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
