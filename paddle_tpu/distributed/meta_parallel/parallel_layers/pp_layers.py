"""Pipeline-parallel layer description + segmentation.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc (:36),
SharedLayerDesc (:76), SegmentLayers (:92), PipelineLayer (:237).

TPU-native notes: segmentation logic is kept 1:1 (seg_method "uniform" or
"layer:ClassName"); execution differs — on the single-controller model all
stages live in one program, so PipelineLayer.forward can run straight through,
and the pipeline engine (pipeline_parallel.py) schedules microbatches as a
compiled loop. Stage-parallel execution over a 'pp' mesh axis uses the
stage-stacked shard_map engine (pipeline_parallel.py PipelineParallel).
"""

from __future__ import annotations

import math
import re

import numpy as np

from ....nn.layer.layers import Layer
from ....nn.layer.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer",
           "PipelineLayerChunk"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("layer_func must be a paddle.nn.Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """reference pp_layers.py:92."""

    def __init__(self, layers_desc, num_parts, method="uniform",
                 num_virtual_pipeline_stage=None):
        self._layers_desc = layers_desc
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(layers_desc)
        if num_virtual_pipeline_stage is not None:
            self.total_parts = num_parts * num_virtual_pipeline_stage
        else:
            self.total_parts = num_parts
        assert self.num_items >= self.num_parts, (
            "layer number should be greater than number of segments")

    def do_segment(self):
        if self.method == "uniform":
            return self.uniform(self.num_items, self.total_parts)
        if self.method.startswith("layer:"):
            cls_name = self.method.split(":")[1]
            weights = [0] * len(self._layers_desc)
            for i, d in enumerate(self._layers_desc):
                name = (d.layer_func.__name__ if isinstance(d, LayerDesc)
                        else d.__class__.__name__)
                if name == cls_name:
                    weights[i] = 1
            actual = sum(weights)
            assert actual >= self.total_parts, (
                f"need at least {self.total_parts} layers of {cls_name}, "
                f"found {actual}")
            # spread the weighted layers evenly over parts
            result = [0] * (self.total_parts + 1)
            memory_counter = 0
            result_idx = 1
            per_part = actual / self.total_parts
            for i, w in enumerate(weights):
                memory_counter += w
                if memory_counter >= per_part * result_idx - 1e-6 and \
                        result_idx <= self.total_parts:
                    result[result_idx] = i + 1
                    result_idx += 1
            result[self.total_parts] = len(weights)
            return result
        raise ValueError(f"unknown seg method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            offset = 1 if i > (num_parts - extra) else 0
            result[i] = result[i - 1] + part_size + offset
        return result


class PipelineLayerChunk(Layer):
    def __init__(self):
        super().__init__()
        self.run_function = []

    def append(self, sublayer):
        if isinstance(sublayer, Layer):
            self.add_sublayer(str(len(self.run_function)), sublayer)
        self.run_function.append(sublayer)

    def forward(self, *args, **kwargs):
        raise NotImplementedError(
            "chunks are executed by the pipeline engine, not called directly")


class PipelineLayer(Layer):
    """reference pp_layers.py:237. Builds ALL layers (single-controller owns
    the whole model); records stage segmentation for the pipeline engine and
    for stage-stacked compilation."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None,
                 **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        self._num_virtual_pipeline_stages = num_virtual_pipeline_stages or 1
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or 1

        seg = SegmentLayers(
            self._layers_desc, num_parts=self._num_stages, method=seg_method,
            num_virtual_pipeline_stage=self._num_virtual_pipeline_stages)
        self.segment_parts = seg.do_segment()

        # build every layer; record shared layers once per key
        self.shared_layers = {}
        self._shared_fwd = {}
        self.run_function = []
        self._stage_of_idx = []
        built = LayerList()
        for idx, d in enumerate(self._layers_desc):
            stage = self._stage_for_index(idx)
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self.shared_layers:
                    self.shared_layers[d.layer_name] = d.build_layer()
                    self._shared_fwd[d.layer_name] = d.forward_func
                layer = self.shared_layers[d.layer_name]
                if d.forward_func is not None:
                    fwd = d.forward_func
                    layer_ref = layer

                    def shared_call(*args, _f=fwd, _l=layer_ref, **kw):
                        return _f(_l, *args, **kw)

                    # let the compiled pp engine find the tied layer's
                    # params behind the closure (pp_scan._chain_params)
                    shared_call.__shared_layer__ = layer_ref
                    self.run_function.append(shared_call)
                    built.append(layer)
                else:
                    self.run_function.append(layer)
                    built.append(layer)
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
                self.run_function.append(layer)
                built.append(layer)
            elif isinstance(d, Layer):
                self.run_function.append(d)
                built.append(d)
            elif callable(d):
                self.run_function.append(d)
            else:
                raise TypeError(f"invalid layer desc {d!r}")
            self._stage_of_idx.append(stage)
        self._built = built

    def _stage_for_index(self, idx):
        parts = self.segment_parts
        for s in range(len(parts) - 1):
            if parts[s] <= idx < parts[s + 1]:
                return s % self._num_stages
        return self._num_stages - 1

    def get_stage_from_index(self, layer_idx):
        return self._stage_of_idx[layer_idx]

    def get_num_virtual_stages(self):
        return self._num_virtual_pipeline_stages

    @property
    def parameters_by_stage(self):
        out = {}
        for idx, fn in enumerate(self.run_function):
            if isinstance(fn, Layer):
                out.setdefault(self._stage_of_idx[idx], []).extend(
                    fn.parameters())
        return out

    def forward(self, input, chunk_id=None):
        """Straight-through execution (all stages in one program)."""
        x = input
        for fn in self.run_function:
            if isinstance(x, tuple):
                x = fn(*x) if not isinstance(fn, Layer) else fn(*x)
            else:
                x = fn(x)
        return x

    def loss(self, output, label):
        if self._loss_fn is None:
            return output
        return self._loss_fn(output, label)
