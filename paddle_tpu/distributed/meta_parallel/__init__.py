"""paddle.distributed.fleet.meta_parallel namespace
(reference: fleet/meta_parallel/__init__.py)."""

from .hybrid_parallel_optimizer import (  # noqa: F401
    HybridParallelClipGrad, HybridParallelOptimizer,
)
from .meta_parallel_base import (  # noqa: F401
    MetaParallelBase, SegmentParallel, ShardingParallel, TensorParallel,
)
from .parallel_layers.mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .parallel_layers.pp_layers import (  # noqa: F401
    LayerDesc, PipelineLayer, PipelineLayerChunk, SegmentLayers,
    SharedLayerDesc,
)
from .parallel_layers.random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)
from .pipeline_parallel import (  # noqa: F401
    PipelineParallel, PipelineParallelWithInterleave,
)
