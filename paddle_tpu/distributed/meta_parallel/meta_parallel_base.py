"""Model wrappers per strategy.

Reference: fleet/model.py:32 distributed_model + fleet/meta_parallel/
meta_parallel_base.py, tensor_parallel.py, sharding_parallel.py,
segment_parallel.py. Wrapping mostly annotates/validates — gradient sync is
by construction in the GSPMD world.
"""

from __future__ import annotations

from ...nn.layer.layers import Layer

__all__ = ["MetaParallelBase", "TensorParallel", "ShardingParallel",
           "SegmentParallel", "wrap_distributed_model"]


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, *args, **kwargs):
        return self._layers.parameters(*args, **kwargs)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self


class TensorParallel(MetaParallelBase):
    """reference tensor_parallel.py — broadcasts mp params at init (moot on
    single controller) and syncs gradients (automatic)."""
    pass


class ShardingParallel(MetaParallelBase):
    pass


class SegmentParallel(MetaParallelBase):
    """reference segment_parallel.py:26 — sequence split over the sep axis.
    The wrapper annotates each input's sequence dim (dim 1) with a 'sep'
    sharding constraint, so under trace GSPMD splits the sequence across the
    sep group (the reference scatters explicitly in the wrapper); grads sync
    automatically over the fused data+sep groups (topology.py:246)."""

    def forward(self, *inputs, **kwargs):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ...core.tensor import Tensor

        mesh = self._hcg.mesh
        sep = mesh.shape.get("sep", 1)
        if sep > 1:
            new_inputs = []
            for t in inputs:
                if (isinstance(t, Tensor) and t.ndim >= 2
                        and isinstance(t._data, jax.core.Tracer)
                        and t.shape[1] % sep == 0):
                    spec = [None] * t.ndim
                    spec[1] = "sep"
                    arr = jax.lax.with_sharding_constraint(
                        t._data, NamedSharding(mesh, P(*spec)))
                    nt = Tensor._wrap(arr)
                    nt.stop_gradient = t.stop_gradient
                    nt._node, nt._out_idx = t._node, t._out_idx
                    t = nt
                new_inputs.append(t)
            inputs = tuple(new_inputs)
        return self._layers(*inputs, **kwargs)


def wrap_distributed_model(model, hcg, strategy):
    from ..parallel import DataParallel
    from .parallel_layers.pp_layers import PipelineLayer
    from .pipeline_parallel import (
        PipelineParallel,
        PipelineParallelWithInterleave,
    )

    if hcg is None:
        return model
    if hcg.get_pipe_parallel_world_size() > 1 or isinstance(model,
                                                            PipelineLayer):
        if isinstance(model, PipelineLayer):
            if model.get_num_virtual_stages() > 1:
                return PipelineParallelWithInterleave(model, hcg, strategy)
            return PipelineParallel(model, hcg, strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, strategy)
    if hcg.get_sep_parallel_world_size() > 1:
        return SegmentParallel(model, hcg, strategy)
    if hcg.get_sharding_parallel_world_size() > 1:
        return ShardingParallel(model, hcg, strategy)
    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model)
    return model
