"""Compiled pipeline-parallel engine: stage-scan with ppermute handoff.

Reference semantics: fleet/meta_parallel/pipeline_parallel.py —
PipelineParallel.forward_backward_pipeline (1F1B, :440) and
PipelineParallelWithInterleave (VPP/circular, :906), driven from host
Python with NCCL isend/irecv (pp_utils/p2p_communication.py:313).

TPU-native redesign (SURVEY §7.1): the whole pipeline is ONE compiled XLA
program. Transformer blocks are stacked along a leading dim that is sharded
over the 'pp' mesh axis, so each stage's weights live ONLY on its pp ranks.
The microbatch schedule is a `lax.scan` whose per-step body computes one
chunk per stage and rotates activations to the next stage with
`lax.ppermute` (this is the reference's isend/irecv pair, compiled onto
ICI). `jax.grad` of the scanned forward IS the pipelined backward — the
ppermute transposes to the reverse rotation, giving the reverse schedule
the reference hand-writes. Per-block rematerialisation (`jax.checkpoint`)
gives the 1F1B-like activation footprint (store only block boundaries,
recompute interiors in the backward wave).

Interleaved/VPP (circular) schedule: with V virtual stages per device,
device ``s`` holds chunks for virtual stages ``v*S + s``; the SAME +1
rotation implements the handoff between consecutive virtual stages because
virtual stage k lives on device ``k % S``. Bubble shrinks from (S-1)/M to
(S-1)/(M*V) steps, exactly the reference's motivation for VPP.

Model contract: the engine auto-detects the longest run of structurally
identical layers (the transformer blocks) in a PipelineLayer. Blocks are
pipelined; the prologue (e.g. embedding) and epilogue (e.g. head + loss)
run at jit level under GSPMD, replicated over 'pp' (their FLOPs are a few
percent of the block stack; placing them is not worth breaking the uniform
activation shape the rotation needs).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ...utils import functional_call, params_dict

__all__ = ["PipelineStageScan", "PipelineScanUnsupported",
           "split_prologue_blocks_epilogue"]


class PipelineScanUnsupported(ValueError):
    """The model has no pipelinable uniform block stack — callers may fall
    back to the grad-accumulation engine. Config errors (divisibility of
    microbatches/blocks) raise plain ValueError and must NOT be swallowed."""


def _signature(layer):
    pd = params_dict(layer, include_buffers=True)
    return (type(layer).__name__,
            tuple(sorted((k, tuple(v.shape), str(v.dtype))
                         for k, v in pd.items())))


def split_prologue_blocks_epilogue(entries, min_blocks=2):
    """Find the longest contiguous run of structurally identical Layers —
    the pipelined block stack. Returns (prologue, blocks, epilogue) as
    sub-lists of `entries`."""
    sigs = []
    for e in entries:
        if isinstance(e, Layer) and params_dict(e):
            sigs.append(_signature(e))
        else:
            sigs.append(None)
    best = (0, 0)  # (start, length)
    i = 0
    while i < len(sigs):
        if sigs[i] is None:
            i += 1
            continue
        j = i
        while j < len(sigs) and sigs[j] == sigs[i]:
            j += 1
        if j - i > best[1]:
            best = (i, j - i)
        i = j
    start, length = best
    if length < min_blocks:
        raise PipelineScanUnsupported(
            "PipelineStageScan needs a run of >=2 structurally identical "
            "layers to pipeline; got none (use the grad-accumulation "
            "fallback engine)")
    return (list(entries[:start]), list(entries[start:start + length]),
            list(entries[start + length:]))


def _entry_layer(e):
    """The Layer whose params an entry uses: the entry itself, or the tied
    layer behind a SharedLayerDesc forward_func closure."""
    if isinstance(e, Layer):
        return e
    return getattr(e, "__shared_layer__", None)


def _chain_params(entries, prefix):
    """(param arrays, buffer arrays, name -> Tensor map for grad
    write-back) for a prologue/epilogue chain. Tied layers referenced
    through SharedLayerDesc closures contribute their params too (their
    grads from both uses accumulate into the same Tensor)."""
    arrays, buffers, tensors = {}, {}, {}
    for i, e in enumerate(entries):
        layer = _entry_layer(e)
        if layer is None:
            continue
        for name, p in layer.named_parameters():
            key = f"{prefix}{i}.{name}"
            arrays[key] = p._data
            tensors[key] = p
        for name, b in layer.named_buffers():
            buffers[f"{prefix}{i}.{name}"] = b._data
    return arrays, buffers, tensors


def _chain_apply(entries, prefix, params, buffers, x):
    """Functionally apply a chain of layers/callables to activation x."""
    from ...core import state as _state
    from ...utils.functional_call import _bound

    h = x
    for i, e in enumerate(entries):
        layer = _entry_layer(e)
        if layer is not None:
            pre = f"{prefix}{i}."
            sub = {k[len(pre):]: v for k, v in params.items()
                   if k.startswith(pre)}
            sub.update({k[len(pre):]: v for k, v in buffers.items()
                        if k.startswith(pre)})
            if isinstance(e, Layer):
                h = functional_call(e, sub, h)
            else:
                # SharedLayerDesc closure: bind the tied layer's params,
                # then run the custom forward_func
                with _bound(layer, sub), _state.trace_guard():
                    out = e(Tensor._wrap(h))
                h = out._data if isinstance(out, Tensor) else out
        else:
            out = e(Tensor._wrap(h))
            h = out._data if isinstance(out, Tensor) else out
    return h


class PipelineStageScan:
    """Compiled pp engine over `mesh` axis `axis` ('pp').

    Parameters live in the owning PipelineLayer's Tensors; every
    `loss_and_grads` call re-reads them (so the eager optimizer keeps
    working) and writes gradients back into `.grad`.
    """

    def __init__(self, pipeline_layer, mesh, axis="pp", num_micro=1,
                 num_virtual=1, remat=True, block_param_spec=None):
        self.layer = pipeline_layer
        self.mesh = mesh
        self.axis = axis
        # optional hybrid-parallel hook: name -> per-dim mesh-axis tuple for
        # the UNSTACKED block param (e.g. Megatron tp plan). The stacked
        # array is then sharded P(pp, *spec); shard_map keeps pp manual and
        # GSPMD handles the tp axes inside the stage body.
        self.block_param_spec = block_param_spec
        self.S = mesh.shape[axis]
        self.V = int(num_virtual)
        self.M = int(num_micro)
        self.remat = remat
        if self.V > 1 and self.M % self.S != 0:
            raise ValueError(
                f"interleaved schedule needs num_micro ({self.M}) divisible "
                f"by pp degree ({self.S})")

        pro, blocks, epi = split_prologue_blocks_epilogue(
            pipeline_layer.run_function)
        L = len(blocks)
        if L % (self.S * self.V) != 0:
            raise ValueError(
                f"{L} blocks not divisible by pp*virtual "
                f"({self.S}*{self.V})")
        self.blocks = blocks
        self.bpc = L // (self.S * self.V)  # blocks per chunk
        self.prologue, self.epilogue = pro, epi
        self.template = blocks[0]

        # stacked order: device-major (s), then chunk (v), then block-in-chunk
        # — so a contiguous S-way shard of dim 0 gives device s exactly its
        # chunks v=0..V-1 back-to-back (virtual stage v*S + s)
        order = []
        for s in range(self.S):
            for v in range(self.V):
                k = v * self.S + s
                order.extend(range(k * self.bpc, (k + 1) * self.bpc))
        self.order = order

        self._block_param_names = sorted(params_dict(self.template))
        self._block_buffer_names = sorted(
            set(params_dict(self.template, include_buffers=True))
            - set(self._block_param_names))
        self._compiled = {}
        self._cache = None  # (token, refs, marshalled) — see gather_params

    # ---- parameter marshalling ----------------------------------------
    def gather_params(self):
        """Marshal current weights into (prologue params, stacked+sharded
        block params, epilogue params, buffers triple). Cached between
        calls until any source array is rebound (optimizer step), keyed on
        the identity of every source buffer — the cache holds references
        so ids cannot be recycled."""
        all_tensors = []
        for e in self.prologue + self.blocks + self.epilogue:
            layer = _entry_layer(e)
            if layer is not None:
                all_tensors.extend(
                    p._data for _, p in layer.named_parameters())
                all_tensors.extend(
                    b._data for _, b in layer.named_buffers())
        token = tuple(map(id, all_tensors))
        if self._cache is not None and self._cache[0] == token:
            return self._cache[2]

        pro_p, pro_b, self._pro_tensors = _chain_params(self.prologue, "pro")
        epi_p, epi_b, self._epi_tensors = _chain_params(self.epilogue, "epi")
        per_block = [params_dict(b, include_buffers=True)
                     for b in self.blocks]

        def sharding_for(name, arr):
            inner = (None,) * (arr.ndim - 1)
            if self.block_param_spec is not None:
                spec = tuple(self.block_param_spec(name) or inner)
                if len(spec) == arr.ndim - 1 and all(
                        s is None
                        or arr.shape[i + 1] % self.mesh.shape[s] == 0
                        for i, s in enumerate(spec)):
                    inner = spec
            return NamedSharding(self.mesh, P(self.axis, *inner))

        def stack(names):
            out = {}
            for name in names:
                arr = jnp.stack([per_block[i][name] for i in self.order])
                out[name] = jax.device_put(arr, sharding_for(name, arr))
            return out

        stacked = stack(self._block_param_names)
        stacked_buf = stack(self._block_buffer_names)
        out = (pro_p, stacked, epi_p, (pro_b, stacked_buf, epi_b))
        self._cache = (token, all_tensors, out)
        return out

    def write_grads(self, pro_g, stacked_g, epi_g, scale=1.0):
        def add_grad(t, g):
            # scale in f32 first — scaling after the cast overflows fp16
            g = (jnp.asarray(g, jnp.float32) * scale).astype(t._data.dtype)
            if t.grad is None:
                t.grad = Tensor._wrap(g)
            else:
                t.grad._rebind(t.grad._data + g)

        for key, t in self._pro_tensors.items():
            add_grad(t, pro_g[key])
        for key, t in self._epi_tensors.items():
            add_grad(t, epi_g[key])
        block_tensors = [dict(b.named_parameters()) for b in self.blocks]
        for name in self._block_param_names:
            g = stacked_g[name]
            for j, orig in enumerate(self.order):
                add_grad(block_tensors[orig][name], g[j])

    # ---- the compiled pipeline ----------------------------------------
    def _pipelined(self, stacked, stacked_buf, h_mb):
        """h_mb: [M, mb, ...] hidden-state microbatches (pp-replicated).
        Returns last-virtual-stage outputs [M, mb, ...]."""
        S, V, M, bpc, axis = self.S, self.V, self.M, self.bpc, self.axis
        T = M * V + S - 1
        names = self._block_param_names + self._block_buffer_names
        template = self.template

        def block_apply(block_p, x):
            return functional_call(template, dict(zip(names, block_p)), x)

        if self.remat:
            block_apply = jax.checkpoint(block_apply)

        def chunk_apply(chunk_p, x):
            def body(h, p):
                return block_apply(p, h), None
            h, _ = jax.lax.scan(body, x, chunk_p)
            return h

        def local(stk_p, stk_b, mbs):
            # leaves: [V*bpc, ...] = this device's blocks, v-major
            stk = {**stk_p, **stk_b}
            s = jax.lax.axis_index(axis)
            state = jnp.zeros_like(mbs[0])
            outbuf = jnp.zeros_like(mbs)

            def step(carry, t):
                state, outbuf = carry
                u = t - s
                uc = jnp.maximum(u, 0)
                q = uc // S
                g = q // V
                v = q % V
                m = g * S + uc % S
                mc = jnp.clip(m, 0, M - 1)
                valid = (u >= 0) & (m < M)
                fresh = (s == 0) & (v == 0)
                inp = jnp.where(
                    fresh,
                    jax.lax.dynamic_index_in_dim(mbs, mc, 0, False),
                    state)
                chunk = tuple(
                    jax.lax.dynamic_slice_in_dim(stk[n], v * bpc, bpc, 0)
                    for n in names)
                y = chunk_apply(chunk, inp)
                y = jnp.where(valid, y, jnp.zeros_like(y))
                is_out = (s == S - 1) & (v == V - 1) & valid
                outbuf = jnp.where(
                    is_out,
                    jax.lax.dynamic_update_index_in_dim(outbuf, y, mc, 0),
                    outbuf)
                nxt = jax.lax.ppermute(
                    y, axis, [(i, (i + 1) % S) for i in range(S)])
                return (nxt, outbuf), None

            (state, outbuf), _ = jax.lax.scan(
                step, (state, outbuf), jnp.arange(T))
            # outputs were collected on the last stage only; replicate
            outbuf = jnp.where(s == S - 1, outbuf, jnp.zeros_like(outbuf))
            return jax.lax.psum(outbuf, axis)

        shmap = jax.shard_map(
            local,
            mesh=self.mesh,
            in_specs=({n: P(axis) for n in self._block_param_names},
                      {n: P(axis) for n in self._block_buffer_names},
                      P()),
            out_specs=P(),
            axis_names={axis},
            check_vma=False,
        )
        return shmap(stacked, stacked_buf, h_mb)

    def _loss_fn(self, pro_p, stacked, epi_p, buffers, x, y):
        pro_b, stacked_buf, epi_b = buffers
        h = _chain_apply(self.prologue, "pro", pro_p, pro_b, x)
        mb = h.shape[0] // self.M
        h_mb = h.reshape((self.M, mb) + h.shape[1:])
        out_mb = self._pipelined(stacked, stacked_buf, h_mb)
        out = out_mb.reshape((self.M * mb,) + out_mb.shape[2:])
        logits = _chain_apply(self.epilogue, "epi", epi_p, epi_b, out)
        loss_fn = self.layer._loss_fn
        if loss_fn is None:
            return jnp.mean(logits)
        loss = loss_fn(Tensor._wrap(logits), Tensor._wrap(y))
        return loss._data if isinstance(loss, Tensor) else loss

    def _get_step(self, with_grad):
        key = ("grad" if with_grad else "fwd")
        if key not in self._compiled:
            if with_grad:
                fn = jax.value_and_grad(self._loss_fn, argnums=(0, 1, 2))
            else:
                fn = self._loss_fn
            self._compiled[key] = jax.jit(fn)
        return self._compiled[key]

    # ---- public API ----------------------------------------------------
    def forward_backward(self, inputs, labels, scale=1.0):
        """One pipelined fwd+bwd over the whole batch (already containing
        all microbatches along dim 0). Accumulates into .grad; returns the
        scalar loss Tensor."""
        pro_p, stacked, epi_p, buffers = self.gather_params()
        x = inputs._data if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        y = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
        loss, (pro_g, stk_g, epi_g) = self._get_step(True)(
            pro_p, stacked, epi_p, buffers, x, y)
        self.write_grads(pro_g, stk_g, epi_g, scale=scale)
        return Tensor._wrap(loss)

    def eval_loss(self, inputs, labels):
        pro_p, stacked, epi_p, buffers = self.gather_params()
        x = inputs._data if isinstance(inputs, Tensor) else jnp.asarray(inputs)
        y = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
        return Tensor._wrap(
            self._get_step(False)(pro_p, stacked, epi_p, buffers, x, y))

    def stage_placement(self):
        """Map block index -> set of device ids holding its weights (for
        tests asserting per-stage placement)."""
        _, stacked, _, _ = self.gather_params()
        name = self._block_param_names[0]
        arr = stacked[name]
        placement = {}
        for sh in arr.addressable_shards:
            lo = sh.index[0].start or 0
            hi = sh.index[0].stop if sh.index[0].stop is not None else arr.shape[0]
            for j in range(lo, hi):
                placement.setdefault(self.order[j], set()).add(sh.device.id)
        return placement
