"""Pipeline-parallel execution engine.

Reference: fleet/meta_parallel/pipeline_parallel.py —
PipelineParallel.forward_backward_pipeline (1F1B, :440),
PipelineParallelWithInterleave (VPP, :906), p2p helpers
(pp_utils/p2p_communication.py:313).

TPU-native redesign: the reference drives 1F1B from host Python with NCCL
isend/irecv. On the single-controller model all stages live in one XLA
program, so the *semantics* of pipelined training (microbatch loop + grad
accumulation) compile into one program per microbatch step; the host schedule
loop disappears. Stage-parallel placement over a 'pp' mesh axis is expressed
by sharding the stage-stacked weights (see models/gpt-style stage scan) —
XLA's latency-hiding scheduler overlaps the inter-stage transfers, playing
the role of the reference's comm/compute-overlap streams.

train_batch() keeps the reference API: splits the batch into accumulate_steps
microbatches, accumulates grads, steps the optimizer once.
"""

from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel requires a PipelineLayer (reference "
                "pipeline_parallel.py asserts the same)")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1))
        self.num_stages = (hcg.get_pipe_parallel_world_size()
                           if hcg is not None else 1)
        self.stage_id = hcg.get_stage_id() if hcg is not None else 0
        self.total_loss = None

    def is_pipeline_first_stage(self):
        return self.stage_id == 0

    def is_pipeline_last_stage(self):
        return self.stage_id == self.num_stages - 1

    def _split_micro(self, data):
        inputs, labels = data
        n = self.accumulate_steps
        from ...ops.manipulation import split as split_op

        ins = split_op(inputs, n, axis=0) if n > 1 else [inputs]
        labs = split_op(labels, n, axis=0) if n > 1 else [labels]
        return list(zip(ins, labs))

    def forward_backward_pipeline(self, data, scaler=None):
        """Microbatched fwd+bwd with grad accumulation — numerically identical
        to 1F1B (same partial order of accumulation); XLA owns the overlap."""
        micro_batches = self._split_micro(data)
        total = None
        for x, y in micro_batches:
            out = self._layers.forward(x)
            loss = self._layers.loss(out, y)
            scaled = loss * (1.0 / self.accumulate_steps)
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            total = loss if total is None else total + loss.detach()
        self.total_loss = total * (1.0 / self.accumulate_steps)
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is None:
            optimizer.step()
        else:
            scaler.step(optimizer)
            scaler.update()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        micro_batches = self._split_micro(data)
        total = None
        from ...core import state as _state

        with _state.no_grad_guard():
            for x, y in micro_batches:
                out = self._layers.forward(x)
                loss = self._layers.loss(out, y) if compute_loss else out
                total = loss if total is None else total + loss
        if compute_loss:
            return total * (1.0 / self.accumulate_steps)
        return total

    def forward(self, *args, **kwargs):
        return self._layers.forward(*args, **kwargs)


class PipelineParallelWithInterleave(PipelineParallel):
    """VPP (reference :906): virtual stages change placement, not semantics —
    same engine here."""
    pass
