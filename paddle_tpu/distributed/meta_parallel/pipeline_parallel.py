"""Pipeline-parallel execution engine.

Reference: fleet/meta_parallel/pipeline_parallel.py —
PipelineParallel.forward_backward_pipeline (1F1B, :440),
PipelineParallelWithInterleave (VPP, :906), p2p helpers
(pp_utils/p2p_communication.py:313).

TPU-native redesign: when the topology has a real 'pp' axis the engine
compiles the whole pipeline into ONE XLA program — stage-stacked block
weights sharded over 'pp', microbatch schedule as a `lax.scan` whose steps
rotate activations between stages with `lax.ppermute`, and `jax.grad`
through the scan as the reverse (1F1B-ordered) schedule. See pp_scan.py.
With pp degree 1 (or a model with no uniform block stack) it falls back to
the microbatch grad-accumulation loop, which is numerically GPipe-identical
but has no stage placement.

train_batch() keeps the reference API: splits the batch into accumulate_steps
microbatches, accumulates grads, steps the optimizer once.
"""

from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel(MetaParallelBase):
    _num_virtual = 1  # overridden by the interleaved engine

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "PipelineParallel requires a PipelineLayer (reference "
                "pipeline_parallel.py asserts the same)")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1))
        self.num_stages = (hcg.get_pipe_parallel_world_size()
                           if hcg is not None else 1)
        self.stage_id = hcg.get_stage_id() if hcg is not None else 0
        self.total_loss = None
        self._scan_engine = None
        self._scan_engine_failed = False

    def _get_scan_engine(self):
        """Build (once) the compiled stage-scan engine; None if the
        topology has no pp axis or the model has no uniform block stack."""
        if self._scan_engine is not None:
            return self._scan_engine
        if self._scan_engine_failed or self.num_stages <= 1:
            return None
        mesh = getattr(self._hcg, "mesh", None)
        if mesh is None or "pp" not in mesh.shape:
            self._scan_engine_failed = True
            return None
        from .pp_scan import PipelineScanUnsupported, PipelineStageScan

        try:
            self._scan_engine = PipelineStageScan(
                self._layers, mesh, axis="pp",
                num_micro=self.accumulate_steps,
                num_virtual=self._num_virtual)
        except PipelineScanUnsupported as e:
            # legitimate fallback: no uniform block stack to pipeline.
            # Config errors (ValueError) propagate — silently dropping the
            # configured pipeline placement would hide real mistakes.
            import warnings

            warnings.warn(
                f"pipeline stage-scan unavailable ({e}); falling back to "
                "the grad-accumulation engine (no stage placement)")
            self._scan_engine_failed = True
            return None
        return self._scan_engine

    def is_pipeline_first_stage(self):
        return self.stage_id == 0

    def is_pipeline_last_stage(self):
        return self.stage_id == self.num_stages - 1

    def _split_micro(self, data):
        inputs, labels = data
        n = self.accumulate_steps
        from ...ops.manipulation import split as split_op

        ins = split_op(inputs, n, axis=0) if n > 1 else [inputs]
        labs = split_op(labels, n, axis=0) if n > 1 else [labels]
        return list(zip(ins, labs))

    def forward_backward_pipeline(self, data, scaler=None):
        """Pipelined fwd+bwd. With a real pp axis: the compiled stage-scan
        (one XLA program, ppermute handoff — pp_scan.py). Otherwise:
        microbatch grad accumulation, numerically GPipe-identical."""
        engine = self._get_scan_engine()
        if engine is not None:
            inputs, labels = data
            scale = (float(scaler._scale)
                     if scaler is not None and scaler._enable else 1.0)
            self.total_loss = engine.forward_backward(
                inputs, labels, scale=scale)
            return self.total_loss
        micro_batches = self._split_micro(data)
        total = None
        for x, y in micro_batches:
            out = self._layers.forward(x)
            loss = self._layers.loss(out, y)
            scaled = loss * (1.0 / self.accumulate_steps)
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            total = loss if total is None else total + loss.detach()
        self.total_loss = total * (1.0 / self.accumulate_steps)
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is None:
            optimizer.step()
        else:
            scaler.step(optimizer)
            scaler.update()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        engine = self._get_scan_engine()
        if engine is not None and compute_loss:
            inputs, labels = data
            return engine.eval_loss(inputs, labels)
        micro_batches = self._split_micro(data)
        total = None
        from ...core import state as _state

        outs = []
        with _state.no_grad_guard():
            for x, y in micro_batches:
                out = self._layers.forward(x)
                if compute_loss:
                    loss = self._layers.loss(out, y)
                    total = loss if total is None else total + loss
                else:
                    outs.append(out)
        if compute_loss:
            return total * (1.0 / self.accumulate_steps)
        if len(outs) == 1:
            return outs[0]
        from ...ops.manipulation import concat

        return concat(outs, axis=0)

    def forward(self, *args, **kwargs):
        return self._layers.forward(*args, **kwargs)


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved (VPP) schedule, reference :906. Each pp rank holds
    `num_virtual_pipeline_stages` chunks (virtual stage k on device k % S);
    the circular rotation in pp_scan.py implements the inter-chunk handoff,
    shrinking the bubble from (S-1)/M to (S-1)/(M*V) steps."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        self._num_virtual = layers.get_num_virtual_stages()
