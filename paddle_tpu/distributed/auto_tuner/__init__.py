"""Hybrid-parallel auto-tuner.

Reference: python/paddle/distributed/auto_tuner/ (tuner.py:21 AutoTuner,
search.py:31 GridSearch, recorder.py History) — enumerate
dp/mp/pp/sharding/micro-batch configurations, launch a trial job per config,
record throughput, report the best.

TPU-native: trial execution is injected (``trial_fn``) — locally a trial is
an in-process compile+measure on the CPU mesh or one chip; in production the
caller launches a job per config. The search/prune/record machinery is the
part the framework owns, and it prunes with the TPU constraints (degrees
must factor the device count; mp and pp must divide layer/hidden dims).
"""

from __future__ import annotations

import csv
import itertools
import time

__all__ = ["AutoTuner", "GridSearch", "Recorder", "default_candidates"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(tuner_cfg):
    """Candidate values per dimension (ref search.py default space)."""
    n = int(tuner_cfg.get("num_gpus", tuner_cfg.get("num_devices", 1)))
    divs = _divisors(n)
    return {
        "dp_degree": tuner_cfg.get("dp_degree", divs),
        "mp_degree": tuner_cfg.get("mp_degree", divs),
        "pp_degree": tuner_cfg.get("pp_degree", divs),
        "sharding_degree": tuner_cfg.get("sharding_degree", [1]),
        "sharding_stage": tuner_cfg.get("sharding_stage", [1]),
        "micro_batch_size": tuner_cfg.get(
            "micro_batch_size",
            _divisors(int(tuner_cfg.get("global_batch_size", 1)))),
        "use_recompute": tuner_cfg.get("use_recompute", [False]),
    }


class GridSearch:
    """ref search.py:31 — exhaustive product with pruning."""

    def __init__(self, tuner_cfg):
        self.cfg = tuner_cfg
        self.space = default_candidates(tuner_cfg)
        self.all_tasks = self._enumerate()
        self.idx = 0

    def _valid(self, c):
        n = int(self.cfg.get("num_gpus", self.cfg.get("num_devices", 1)))
        degrees = (c["dp_degree"] * c["mp_degree"] * c["pp_degree"]
                   * c["sharding_degree"])
        if degrees != n:
            return False
        gbs = int(self.cfg.get("global_batch_size", 0))
        if gbs:
            per_dp = gbs // (c["dp_degree"] * c["sharding_degree"])
            if per_dp * c["dp_degree"] * c["sharding_degree"] != gbs:
                return False
            if per_dp % c["micro_batch_size"] != 0:
                return False
        layers = int(self.cfg.get("num_layers", 0))
        if layers and layers % c["pp_degree"] != 0:
            return False
        heads = int(self.cfg.get("num_attention_heads", 0))
        if heads and heads % c["mp_degree"] != 0:
            return False
        vocab = int(self.cfg.get("vocab_size", 0))
        if vocab and vocab % c["mp_degree"] != 0:
            return False
        return True

    def _enumerate(self):
        keys = list(self.space)
        out = []
        for vals in itertools.product(*(self.space[k] for k in keys)):
            c = dict(zip(keys, vals))
            if self._valid(c):
                out.append(c)
        return out

    def search_once(self):
        """Next untried config or None (ref search.py search_once)."""
        if self.idx >= len(self.all_tasks):
            return None
        c = self.all_tasks[self.idx]
        self.idx += 1
        return c


class Recorder:
    """ref recorder.py History — store + sort + csv dump."""

    def __init__(self, metric="throughput", direction="max"):
        self.metric = metric
        self.direction = direction
        self.history = []

    def add_cfg(self, **cfg_and_metric):
        self.history.append(dict(cfg_and_metric))

    def sort_metric(self):
        err = [h for h in self.history if h.get(self.metric) is None]
        ok = [h for h in self.history if h.get(self.metric) is not None]
        ok.sort(key=lambda h: h[self.metric],
                reverse=self.direction == "max")
        self.history = ok + err
        return self.history

    def get_best(self):
        self.sort_metric()
        for h in self.history:
            if h.get(self.metric) is not None:
                return h, False
        return None, True

    def store_history(self, path):
        if not self.history:
            return
        keys = sorted({k for h in self.history for k in h})
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for h in self.history:
                w.writerow(h)


class AutoTuner:
    """ref tuner.py:21 — drive search over trials.

    ``trial_fn(cfg) -> float | None`` runs one configuration and returns the
    metric (None = failed/OOM trial). ``max_time_per_task`` bounds a trial;
    ``max_search_time`` bounds the whole tune.
    """

    def __init__(self, tuner_cfg, trial_fn=None):
        self.cfg = dict(tuner_cfg)
        self.searcher = GridSearch(self.cfg)
        self.recorder = Recorder(
            metric=self.cfg.get("metric_cfg", {}).get("name", "throughput"),
            direction=self.cfg.get("metric_cfg", {}).get(
                "OptimizationDirection", "max"))
        self.trial_fn = trial_fn
        self.cur_task_id = 0

    def search_once(self):
        return self.searcher.search_once()

    def tune(self, max_search_time=None):
        """Run all trials; returns (best_cfg, recorder)."""
        assert self.trial_fn is not None, "provide trial_fn to tune()"
        t0 = time.time()
        while True:
            if max_search_time and time.time() - t0 > max_search_time:
                break
            cfg = self.search_once()
            if cfg is None:
                break
            self.cur_task_id += 1
            try:
                metric = self.trial_fn(dict(cfg))
            except Exception:
                metric = None
            self.recorder.add_cfg(**cfg,
                                  **{self.recorder.metric: metric})
        best, err = self.recorder.get_best()
        return best, self.recorder
