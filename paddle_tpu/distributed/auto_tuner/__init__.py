"""Hybrid-parallel auto-tuner.

Reference: python/paddle/distributed/auto_tuner/ (tuner.py:21 AutoTuner,
search.py:31 GridSearch, recorder.py History) — enumerate
dp/mp/pp/sharding/micro-batch configurations, launch a trial job per config,
record throughput, report the best.

TPU-native: trial execution is injected (``trial_fn``) — locally a trial is
an in-process compile+measure on the CPU mesh or one chip; in production the
caller launches a job per config. The search/prune/record machinery is the
part the framework owns, and it prunes with the TPU constraints (degrees
must factor the device count; mp and pp must divide layer/hidden dims).
"""

from __future__ import annotations

import csv
import itertools
import time

__all__ = ["AutoTuner", "GridSearch", "Recorder", "default_candidates",
           "get_mem", "transformer_params"]


# ---------------------------------------------------------------------------
# Analytic cost model (reference cost_model.py:16-35 — whose all_params/
# all_acts are literal `return 1` stubs; this is the real accounting the
# stubs reserve space for). Units: bytes, converted to GB at the end.
# ---------------------------------------------------------------------------

def transformer_params(h, l, V):
    """Parameter count of a GPT/Llama-class decoder: embedding V*h, per
    layer 4h^2 (attention) + 8h^2 (MLP) + ~13h (norms/biases), final norm."""
    return V * h + l * (12 * h * h + 13 * h) + h


def get_mem(total_cards, parallel_cfg, l, h, a, V, s, gbs,
            bytes_per_param=2, optimizer_bytes_per_param=12):
    """Estimated peak per-device GB under a hybrid-parallel config.

    Accounting (bf16 params + fp32 Adam master/moments by default):
    - weights shard over mp*pp (embedding over mp), bytes_per_param each;
    - grads: bytes_per_param, sharded additionally by sharding_degree at
      stage >= 2;
    - optimizer state (master + 2 moments = 12 B/param fp32): divided by
      sharding_degree from stage 1 on;
    - activations per layer per microbatch: s*b*h*(34 + 5*a*s/h) bytes at
      2 B/elem (Korthikanti et al. 2022 eq. 2), layers/pp per stage, mp
      divides; full recompute keeps only the ~2*s*b*h layer boundaries.
      vpp holds (1 + (pp-1)/(pp*vpp)) times one stage's activations.
    """
    mp = int(parallel_cfg.get("mp_degree", 1))
    pp = int(parallel_cfg.get("pp_degree", 1))
    sharding = int(parallel_cfg.get("sharding_degree", 1))
    stage = int(parallel_cfg.get("sharding_stage", 1))
    b = int(parallel_cfg.get("micro_batch_size", 1))
    vpp = int(parallel_cfg.get("vpp_degree", 1))
    recompute = bool(parallel_cfg.get("use_recompute", False))

    n_params = transformer_params(h, l, V)
    local_params = n_params / (mp * pp)

    param_bytes = local_params * bytes_per_param
    grad_bytes = local_params * bytes_per_param
    opt_bytes = local_params * optimizer_bytes_per_param
    if stage >= 1:
        opt_bytes /= sharding
    if stage >= 2:
        grad_bytes /= sharding
    if stage >= 3:
        param_bytes /= sharding

    layers_per_stage = max(l // pp, 1)
    if recompute:
        act_per_layer = 2.0 * s * b * h / mp
    else:
        act_per_layer = s * b * h * (34.0 + 5.0 * a * s / h) / mp
    vpp_ratio = 1.0 + (pp - 1.0) / (pp * vpp) if vpp > 1 else 1.0
    # 1F1B: a stage holds up to `pp` in-flight microbatches of activations,
    # bounded by the microbatches each PIPELINE actually runs: the global
    # batch splits over dp*sharding replicas first, then into microbatches
    dp = int(parallel_cfg.get("dp_degree", 1))
    num_micro = max(int(gbs // max(b * dp * sharding, 1)), 1)
    in_flight = min(pp, num_micro)
    act_bytes = act_per_layer * layers_per_stage * vpp_ratio * in_flight

    return (param_bytes + grad_bytes + opt_bytes + act_bytes) / (2 ** 30)


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def default_candidates(tuner_cfg):
    """Candidate values per dimension (ref search.py default space)."""
    n = int(tuner_cfg.get("num_gpus", tuner_cfg.get("num_devices", 1)))
    divs = _divisors(n)
    return {
        "dp_degree": tuner_cfg.get("dp_degree", divs),
        "mp_degree": tuner_cfg.get("mp_degree", divs),
        "pp_degree": tuner_cfg.get("pp_degree", divs),
        "sharding_degree": tuner_cfg.get("sharding_degree", [1]),
        "sharding_stage": tuner_cfg.get("sharding_stage", [1]),
        "micro_batch_size": tuner_cfg.get(
            "micro_batch_size",
            _divisors(int(tuner_cfg.get("global_batch_size", 1)))),
        "use_recompute": tuner_cfg.get("use_recompute", [False]),
    }


class GridSearch:
    """ref search.py:31 — exhaustive product with pruning."""

    def __init__(self, tuner_cfg):
        self.cfg = tuner_cfg
        self.space = default_candidates(tuner_cfg)
        self.all_tasks = self._enumerate()
        self.idx = 0

    def _valid(self, c):
        n = int(self.cfg.get("num_gpus", self.cfg.get("num_devices", 1)))
        degrees = (c["dp_degree"] * c["mp_degree"] * c["pp_degree"]
                   * c["sharding_degree"])
        if degrees != n:
            return False
        gbs = int(self.cfg.get("global_batch_size", 0))
        if gbs:
            per_dp = gbs // (c["dp_degree"] * c["sharding_degree"])
            if per_dp * c["dp_degree"] * c["sharding_degree"] != gbs:
                return False
            if per_dp % c["micro_batch_size"] != 0:
                return False
        layers = int(self.cfg.get("num_layers", 0))
        if layers and layers % c["pp_degree"] != 0:
            return False
        heads = int(self.cfg.get("num_attention_heads", 0))
        if heads and heads % c["mp_degree"] != 0:
            return False
        vocab = int(self.cfg.get("vocab_size", 0))
        if vocab and vocab % c["mp_degree"] != 0:
            return False
        return True

    def _enumerate(self):
        keys = list(self.space)
        out = []
        for vals in itertools.product(*(self.space[k] for k in keys)):
            c = dict(zip(keys, vals))
            if self._valid(c):
                out.append(c)
        return out

    def search_once(self):
        """Next untried config or None (ref search.py search_once)."""
        if self.idx >= len(self.all_tasks):
            return None
        c = self.all_tasks[self.idx]
        self.idx += 1
        return c


class Recorder:
    """ref recorder.py History — store + sort + csv dump."""

    def __init__(self, metric="throughput", direction="max"):
        self.metric = metric
        self.direction = direction
        self.history = []

    def add_cfg(self, **cfg_and_metric):
        self.history.append(dict(cfg_and_metric))

    def sort_metric(self):
        err = [h for h in self.history if h.get(self.metric) is None]
        ok = [h for h in self.history if h.get(self.metric) is not None]
        ok.sort(key=lambda h: h[self.metric],
                reverse=self.direction == "max")
        self.history = ok + err
        return self.history

    def get_best(self):
        self.sort_metric()
        for h in self.history:
            if h.get(self.metric) is not None:
                return h, False
        return None, True

    def store_history(self, path):
        if not self.history:
            return
        keys = sorted({k for h in self.history for k in h})
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            for h in self.history:
                w.writerow(h)


class AutoTuner:
    """ref tuner.py:21 — drive search over trials.

    ``trial_fn(cfg) -> float | None`` runs one configuration and returns the
    metric (None = failed/OOM trial). ``max_time_per_task`` bounds a trial;
    ``max_search_time`` bounds the whole tune.
    """

    def __init__(self, tuner_cfg, trial_fn=None):
        self.cfg = dict(tuner_cfg)
        self.searcher = GridSearch(self.cfg)
        self.recorder = Recorder(
            metric=self.cfg.get("metric_cfg", {}).get("name", "throughput"),
            direction=self.cfg.get("metric_cfg", {}).get(
                "OptimizationDirection", "max"))
        self.trial_fn = trial_fn
        self.cur_task_id = 0

    def search_once(self):
        return self.searcher.search_once()

    def estimate_mem_gb(self, cfg):
        """Analytic per-device memory estimate for a config, or None when
        the tuner_cfg lacks the model dims (hidden_size etc.)."""
        c = self.cfg
        dims = {k: c.get(k) for k in ("num_layers", "hidden_size",
                                      "num_attention_heads", "vocab_size",
                                      "seq_length", "global_batch_size")}
        if not all(dims.values()):
            return None
        return get_mem(
            int(c.get("num_gpus", c.get("num_devices", 1))), cfg,
            l=int(dims["num_layers"]), h=int(dims["hidden_size"]),
            a=int(dims["num_attention_heads"]), V=int(dims["vocab_size"]),
            s=int(dims["seq_length"]), gbs=int(dims["global_batch_size"]))

    def tune(self, max_search_time=None):
        """Run all trials; returns (best_cfg, recorder). Configs whose
        analytic memory estimate exceeds ``memory_limit_gb`` (when set) are
        pruned WITHOUT trialing and recorded with pruned='mem_estimate'
        (reference cost_model.py:16 intent; recorder keeps the audit
        trail)."""
        assert self.trial_fn is not None, "provide trial_fn to tune()"
        budget = self.cfg.get("memory_limit_gb")
        t0 = time.time()
        while True:
            if max_search_time and time.time() - t0 > max_search_time:
                break
            cfg = self.search_once()
            if cfg is None:
                break
            est = self.estimate_mem_gb(cfg)
            if budget is not None and est is not None and est > budget:
                self.recorder.add_cfg(**cfg, mem_estimate_gb=round(est, 3),
                                      pruned="mem_estimate",
                                      **{self.recorder.metric: None})
                continue
            self.cur_task_id += 1
            try:
                metric = self.trial_fn(dict(cfg))
            except Exception:
                metric = None
            rec = dict(cfg, **{self.recorder.metric: metric})
            if est is not None:
                rec["mem_estimate_gb"] = round(est, 3)
            self.recorder.add_cfg(**rec)
        best, err = self.recorder.get_best()
        return best, self.recorder
