"""DataParallel.

Reference: python/paddle/distributed/parallel.py (DataParallel class +
C++ EagerReducer gradient bucketing, collective/reducer.h:88).

TPU-native: in the single-controller model the batch is a global array
sharded over 'dp'; gradients of replicated parameters are reduced by XLA
inside the compiled step — there is no reducer, no buckets, no overlap hooks
to manage (SURVEY.md §3.4 translation note). The wrapper preserves API:
scale_loss, no_sync, find_unused_parameters.
"""

from __future__ import annotations

import contextlib

from ..nn.layer.layers import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self.group = group

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        # gradient sync happens inside the compiled step; nothing to defer
        yield

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, *args, **kwargs):
        return self._layers.parameters(*args, **kwargs)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)
