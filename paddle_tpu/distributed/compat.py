"""Long-tail distributed-namespace parity: enums, PS entry configs,
legacy datasets, split(), process-group introspection, gloo helpers.

Reference sites:
- ParallelMode: python/paddle/distributed/parallel.py:123
- entry attrs: python/paddle/distributed/entry_attr.py:61-154
- InMemoryDataset/QueueDataset: distributed/fleet/dataset/dataset.py:352,1295
- split: distributed/fleet/layers/mpu/mp_ops.py:700
- destroy_process_group/is_available/get_backend: distributed/collective.py
- ReduceType/DistAttr: auto_parallel placement/static dist_attr
- gloo_*: python/paddle/distributed/parallel_with_gloo.py
"""

from __future__ import annotations

__all__ = [
    "ParallelMode", "ReduceType", "DistAttr", "ProbabilityEntry",
    "CountFilterEntry", "ShowClickEntry", "InMemoryDataset", "QueueDataset",
    "split", "destroy_process_group", "is_available", "get_backend",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
]


class ParallelMode:
    """reference parallel.py ParallelMode (int enum constants)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class ReduceType:
    """reference phi ReduceType used by Partial placements."""

    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class DistAttr:
    """Static-graph tensor dist attr (reference
    auto_parallel/static/dist_attribute; the dynamic path uses
    placements). Holds (mesh, sharding_specs) — under GSPMD this maps
    directly onto a NamedSharding."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs or [])

    def to_named_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        jmesh = getattr(self.process_mesh, "jax_mesh", self.process_mesh)
        assert isinstance(jmesh, jax.sharding.Mesh)
        return NamedSharding(jmesh, PartitionSpec(*self.sharding_specs))

    def __repr__(self):
        return (f"DistAttr(mesh={self.process_mesh}, "
                f"specs={self.sharding_specs})")


# ---------------------------------------------------------------------------
# PS sparse-table entry configs (consumed by distributed.ps.SparseEmbedding)
# ---------------------------------------------------------------------------

class _EntryAttr:
    def _attr_str(self):
        raise NotImplementedError


class ProbabilityEntry(_EntryAttr):
    """Admit a new sparse feature with given probability
    (entry_attr.py:61)."""

    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self._name = "probability_entry"
        self._probability = probability

    def _attr_str(self):
        return f"{self._name}:{self._probability}"


class CountFilterEntry(_EntryAttr):
    """Admit a sparse feature after it is seen >= count times
    (entry_attr.py:106)."""

    def __init__(self, count):
        if count < 0:
            raise ValueError("count must be >= 0")
        self._name = "count_filter_entry"
        self._count = int(count)

    def _attr_str(self):
        return f"{self._name}:{self._count}"


class ShowClickEntry(_EntryAttr):
    """CTR show/click statistic columns (entry_attr.py:154)."""

    def __init__(self, show_name, click_name):
        if not (isinstance(show_name, str) and isinstance(click_name, str)):
            raise ValueError("show/click names must be strings")
        self._name = "show_click_entry"
        self._show = show_name
        self._click = click_name

    def _attr_str(self):
        return f"{self._name}:{self._show}:{self._click}"


# ---------------------------------------------------------------------------
# legacy PS dataset feeders
# ---------------------------------------------------------------------------

class _DatasetBase:
    """File-list dataset with the reference DatasetBase control surface.
    The reference streams slots through a brpc DataFeed into PS trainers;
    here files hold numpy-parseable lines and loading is host-side (the
    TPU path trains from paddle.io.DataLoader — these classes exist for
    the PaddleRec-style entry points)."""

    def __init__(self):
        self._filelist = []
        self._parse_fn = None
        self._use_var = []
        self._batch_size = 1
        self._records = None

    def init(self, batch_size=1, use_var=None, parse_fn=None, **kwargs):
        self._batch_size = int(batch_size)
        self._use_var = list(use_var or [])
        self._parse_fn = parse_fn

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_use_var(self, use_var):
        self._use_var = list(use_var)

    def _iter_lines(self):
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    yield (self._parse_fn(line) if self._parse_fn
                           else line.split())


class InMemoryDataset(_DatasetBase):
    """reference dataset.py:352 — load files to memory, global shuffle,
    then feed."""

    def load_into_memory(self):
        self._records = list(self._iter_lines())

    def local_shuffle(self):
        self._shuffle()

    def global_shuffle(self, fleet=None, thread_num=None):
        # single-controller: global == local
        self._shuffle()

    def _shuffle(self):
        import numpy as np

        if self._records is None:
            raise RuntimeError("call load_into_memory() first")
        order = np.random.permutation(len(self._records))
        self._records = [self._records[i] for i in order]

    def get_memory_data_size(self, fleet=None):
        return 0 if self._records is None else len(self._records)

    def release_memory(self):
        self._records = None

    def __iter__(self):
        if self._records is None:
            raise RuntimeError("call load_into_memory() first")
        return iter(self._records)


class QueueDataset(_DatasetBase):
    """reference dataset.py:1295 — streaming file reader (no memory
    residency)."""

    def __iter__(self):
        return self._iter_lines()


# ---------------------------------------------------------------------------
# split — Megatron-style parallel op builder (mp_ops.py:700)
# ---------------------------------------------------------------------------

def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Build-and-apply a weight-partitioned embedding/linear.

    The reference constructs c_ops wired to the mp group; here the
    partitioned layer is one of the meta_parallel mp layers, whose weights
    shard over the 'mp' mesh axis under GSPMD. Returns the layer output;
    the constructed layer is attached as ``split.last_layer`` so callers
    can reach the parameters (the reference's functional form implicitly
    registers them on the enclosing Layer)."""
    from .meta_parallel.parallel_layers.mp_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
        VocabParallelEmbedding,
    )

    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
    elif operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1],
                                      weight_attr=weight_attr,
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False)
        elif axis == 1:
            layer = ColumnParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=bool(gather_out))
        else:
            raise ValueError("linear split axis must be 0 or 1")
    else:
        raise ValueError(f"unsupported split operation {operation!r}")
    split.last_layer = layer
    return layer(x)


# ---------------------------------------------------------------------------
# process-group introspection + gloo host helpers
# ---------------------------------------------------------------------------

from .collective import destroy_process_group, is_available  # noqa: F401,E402


def get_backend(group=None):
    import jax

    return "xla:" + jax.default_backend()


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Host-side CPU rendezvous (reference parallel_with_gloo.py). The
    jax.distributed coordination service is the gloo analog; this records
    the rendezvous env the launcher consumes (initialization itself happens
    in the launch bootstrap so single-process runs don't block)."""
    import os

    os.environ.update({
        "PADDLE_TRAINER_ID": str(int(rank_id)),
        "PADDLE_TRAINERS_NUM": str(int(rank_num)),
        "PADDLE_MASTER": str(server_endpoint),
        "MASTER_ADDR": str(server_endpoint).split(":")[0],
    })


def gloo_barrier():
    from .communication import barrier

    barrier()


def gloo_release():
    return None
