"""paddle.distributed — parallelism UX (reference: python/paddle/distributed/).

TPU-native design (SURVEY.md §5.8): no ProcessGroup/NCCL object model — a
single-controller JAX program over a device Mesh. Collective *APIs* are traced
``lax.p*`` ops inside shard_map / GSPMD-sharded jit; ``jax.distributed``'s
coordination service replaces TCPStore for multi-host bring-up.
"""

from __future__ import annotations

import os
import sys

import jax

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "is_initialized",
    "ParallelEnv", "fleet", "DataParallel", "new_group", "get_group",
    "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "alltoall", "alltoall_single", "broadcast", "scatter",
    "gather", "send", "recv", "isend", "irecv", "barrier", "wait", "ReduceOp",
    "P2POp", "batch_isend_irecv", "stream", "shard_tensor", "reshard",
    "shard_layer", "shard_optimizer", "dtensor_from_fn", "unshard_dtensor",
    "ProcessMesh", "Shard", "Replicate", "Partial", "get_mesh", "set_mesh",
    "spawn", "launch", "save_state_dict", "load_state_dict",
    "CheckpointManager",
    "PlanMismatchError",
]

_initialized = False


def init_parallel_env():
    """Reference: python/paddle/distributed/parallel.py:943. Multi-host: uses
    jax.distributed.initialize driven by env (coordinator addr, process id)."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        if not jax.distributed.is_initialized():  # bootstrap.py may have
            port = os.environ.get("MASTER_PORT", "8471")
            jax.distributed.initialize(
                coordinator_address=f"{coord}:{port}",
                num_processes=nprocs,
                process_id=pid,
            )
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0


from .collective import Group, get_group, new_group  # noqa: E402,F401
from .communication import (  # noqa: E402,F401
    P2POp, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, barrier, batch_isend_irecv, broadcast,
    broadcast_object_list, gather, irecv, isend, recv, reduce, reduce_scatter,
    scatter, scatter_object_list, send, stream, wait,
)
all_to_all = alltoall
from .auto_parallel import (  # noqa: E402,F401
    Partial, ProcessMesh, Replicate, Shard, dtensor_from_fn, get_mesh,
    reshard, set_mesh, shard_layer, shard_optimizer, shard_tensor,
    unshard_dtensor,
)
from .auto_parallel.placement import Placement  # noqa: E402,F401
from .parallel import DataParallel  # noqa: E402,F401
from . import fleet  # noqa: E402,F401
from . import plan  # noqa: E402,F401
from .plan import Plan, compile_step_with_plan  # noqa: E402,F401
from . import ps  # noqa: E402,F401
from . import sharding  # noqa: E402,F401
from .checkpoint import (  # noqa: E402,F401
    CheckpointManager, PlanMismatchError, load_state_dict, save_state_dict)
from .collective import destroy_process_group, is_available  # noqa: E402,F401
from .compat import (  # noqa: E402,F401
    CountFilterEntry, InMemoryDataset, ParallelMode, ProbabilityEntry,
    QueueDataset, ReduceType, ShowClickEntry, DistAttr, get_backend,
    gloo_barrier, gloo_init_parallel_env, gloo_release, split,
)
from .dist_model import DistModel, Strategy, to_static  # noqa: E402,F401
from . import io  # noqa: E402,F401

# paddle code imports meta_parallel via fleet.meta_parallel; alias it
from . import meta_parallel as _meta_parallel  # noqa: E402

sys.modules[__name__ + ".fleet.meta_parallel"] = _meta_parallel
fleet.meta_parallel = _meta_parallel


def _spawn_worker(func, args, rank, nprocs, port, device):
    os.environ.update({
        "PADDLE_MASTER": "127.0.0.1",
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(port),
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_LOCAL_RANK": str(rank),
    })
    if device is not None:
        # per-platform visibility vars (jax reads the vendor ones)
        os.environ["CUDA_VISIBLE_DEVICES"] = str(device)
        os.environ["TPU_VISIBLE_DEVICES"] = str(device)
        os.environ["JAX_VISIBLE_DEVICES"] = str(device)  # covers CPU backend
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference: distributed/spawn.py — start nprocs local worker
    processes running ``func(*args)`` with the PADDLE_*/MASTER_* env
    contract (each worker calls init_parallel_env itself).

    nprocs=1 runs func inline (world of 1); nprocs=-1 means one worker
    per entry of options['devices'] (comma list or sequence), falling
    back to 1 — device discovery cannot happen here because importing the
    backend in the parent would break the children's jax.distributed
    ordering. Pass options['devices'] to partition local accelerators
    (sets JAX_VISIBLE_DEVICES per rank); without it workers share the
    parent's device visibility, which on a single-accelerator host only
    works for CPU. `func` must be picklable (module-level) — workers use
    the multiprocessing 'spawn' start method. For script-level launches
    prefer ``python -m paddle_tpu.distributed.launch``."""
    devices = options.get("devices")
    if isinstance(devices, str):
        devices = [d for d in devices.split(",") if d]
    if nprocs == -1:
        nprocs = len(devices) if devices else 1
    if nprocs < 1:
        raise ValueError(f"spawn: invalid nprocs={nprocs}")
    if nprocs == 1:
        func(*args)
        return None
    import multiprocessing as mp
    import time as _time

    from .launch.main import _free_port

    port = _free_port()
    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(
            target=_spawn_worker,
            args=(func, args, rank, nprocs, port,
                  devices[rank % len(devices)] if devices else None),
            daemon=daemon)
        for rank in range(nprocs)
    ]
    for p in procs:
        p.start()
    if not join:
        return procs
    # watch loop: one worker dying (e.g. before the coordinator comes up)
    # must kill the group, not leave the rest blocked in initialize()
    try:
        while True:
            codes = [p.exitcode for p in procs]
            if any(c is not None and c != 0 for c in codes):
                for p in procs:
                    if p.is_alive():
                        p.terminate()
                for p in procs:
                    p.join()
                bad = [c for c in codes if c is not None and c != 0]
                raise RuntimeError(
                    f"spawn: worker(s) failed with exit codes {bad}")
            if all(c == 0 for c in codes):
                return None
            _time.sleep(0.2)
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()


def launch():
    """CLI entry — see paddle_tpu/distributed/launch/main.py."""
    from .launch.main import main

    return main()
