"""paddle.distributed — parallelism UX (reference: python/paddle/distributed/).

TPU-native design (SURVEY.md §5.8): no ProcessGroup/NCCL object model — a
single-controller JAX program over a device Mesh. Collective *APIs* are traced
``lax.p*`` ops inside shard_map / GSPMD-sharded jit; ``jax.distributed``'s
coordination service replaces TCPStore for multi-host bring-up.
"""

from __future__ import annotations

import os
import sys

import jax

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "is_initialized",
    "ParallelEnv", "fleet", "DataParallel", "new_group", "get_group",
    "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "alltoall", "alltoall_single", "broadcast", "scatter",
    "gather", "send", "recv", "isend", "irecv", "barrier", "wait", "ReduceOp",
    "P2POp", "batch_isend_irecv", "stream", "shard_tensor", "reshard",
    "shard_layer", "shard_optimizer", "dtensor_from_fn", "unshard_dtensor",
    "ProcessMesh", "Shard", "Replicate", "Partial", "get_mesh", "set_mesh",
    "spawn", "launch", "save_state_dict", "load_state_dict",
]

_initialized = False


def init_parallel_env():
    """Reference: python/paddle/distributed/parallel.py:943. Multi-host: uses
    jax.distributed.initialize driven by env (coordinator addr, process id)."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        port = os.environ.get("MASTER_PORT", "8471")
        jax.distributed.initialize(
            coordinator_address=f"{coord}:{port}",
            num_processes=nprocs,
            process_id=pid,
        )
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0


from .collective import Group, get_group, new_group  # noqa: E402,F401
from .communication import (  # noqa: E402,F401
    P2POp, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, barrier, batch_isend_irecv, broadcast,
    broadcast_object_list, gather, irecv, isend, recv, reduce, reduce_scatter,
    scatter, scatter_object_list, send, stream, wait,
)
all_to_all = alltoall
from .auto_parallel import (  # noqa: E402,F401
    Partial, ProcessMesh, Replicate, Shard, dtensor_from_fn, get_mesh,
    reshard, set_mesh, shard_layer, shard_optimizer, shard_tensor,
    unshard_dtensor,
)
from .parallel import DataParallel  # noqa: E402,F401
from . import fleet  # noqa: E402,F401
from . import sharding  # noqa: E402,F401
from .checkpoint import load_state_dict, save_state_dict  # noqa: E402,F401

# paddle code imports meta_parallel via fleet.meta_parallel; alias it
from . import meta_parallel as _meta_parallel  # noqa: E402

sys.modules[__name__ + ".fleet.meta_parallel"] = _meta_parallel
fleet.meta_parallel = _meta_parallel


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference: distributed/spawn.py. Single-controller JAX: the launcher
    owns multi-process bring-up; in-process we just call func (world of 1
    per-process semantics are preserved by the collective layer)."""
    func(*args)


def launch():
    from .launch.main import main

    return main()
