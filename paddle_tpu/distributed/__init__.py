"""paddle.distributed — parallelism UX (reference: python/paddle/distributed/).

TPU-native design (SURVEY.md §5.8): no ProcessGroup/NCCL object model — a
single-controller JAX program over a device Mesh. Collective *APIs* are traced
``lax.p*`` ops inside shard_map / GSPMD-sharded jit; ``jax.distributed``'s
coordination service replaces TCPStore for multi-host bring-up.

This module grows across milestones; env/bring-up + rank info live here.
"""

from __future__ import annotations

import os

import jax

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "is_initialized",
           "ParallelEnv"]

_initialized = False


def init_parallel_env():
    """Reference: python/paddle/distributed/parallel.py:943. Multi-host: uses
    jax.distributed.initialize driven by env (coordinator addr, process id)."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        port = os.environ.get("MASTER_PORT", "8471")
        jax.distributed.initialize(
            coordinator_address=f"{coord}:{port}",
            num_processes=nprocs,
            process_id=pid,
        )
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    try:
        from .collective import _default_group

        if _default_group is not None:
            return _default_group.nranks
    except ImportError:
        pass
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    local_rank = rank
    nranks = world_size
