"""ZeRO-style sharding (group_sharded API).

Reference: python/paddle/distributed/sharding/group_sharded.py +
fleet/meta_parallel/sharding/ (GroupShardedOptimizerStage2 :53,
GroupShardedStage2 :46, GroupShardedStage3 :85) and
DygraphShardingOptimizer (stage-1, dygraph_sharding_optimizer.py:48).

TPU-native mapping (SURVEY.md §7.1): named shardings over the 'sharding' mesh
axis express all three stages declaratively —
  stage 1: optimizer moments sharded (dim 0) over 'sharding'
  stage 2: + gradients arrive reduce-scattered (XLA emits this from the
           sharded-moment update)
  stage 3: + parameters themselves sharded; XLA all-gathers on use
           (weights-gather-on-forward, exactly GroupShardedStage3's hooks)
No re-gather hooks, buckets, or broadcast lists — the compiler schedules them.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "shard_accumulators"]


def _sharding_mesh():
    from ..fleet.fleet import fleet_singleton

    try:
        hcg = fleet_singleton.get_hybrid_communicate_group()
        if hcg.get_sharding_parallel_world_size() > 1:
            return hcg.mesh, "sharding"
    except Exception:
        pass
    return None, None


def _shard_dim0(arr, mesh, axis):
    if arr.ndim == 0 or arr.shape[0] % mesh.shape[axis] != 0:
        return arr
    return jax.device_put(arr, NamedSharding(mesh, P(
        axis, *([None] * (arr.ndim - 1)))))


def shard_accumulators(optimizer, mesh=None, axis="sharding"):
    """Stage-1: re-lay optimizer state sharded over the sharding axis."""
    if mesh is None:
        mesh, axis = _sharding_mesh()
    if mesh is None:
        return optimizer
    for store in optimizer._accumulators.values():
        for pid, arr in store.items():
            store[pid] = _shard_dim0(arr, mesh, axis)
    return optimizer


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """Reference group_sharded.py group_sharded_parallel(level in
    {'os', 'os_g', 'p_g_os'}).

    ``offload`` (CPU-offloaded state) is not supported on the TPU backend —
    XLA owns HBM and host offload would serialize every step on PCIe; a
    warning is raised rather than silently ignoring it. ``segment_size`` /
    ``buffer_max_size`` (the reference's comm bucketing knobs) have no
    effect: XLA schedules and fuses the collectives itself."""
    assert level in ("os", "os_g", "p_g_os"), level
    if offload:
        import warnings

        warnings.warn(
            "group_sharded_parallel(offload=True) is unsupported on the TPU "
            "backend; continuing without offload", stacklevel=2)
    mesh, axis = _sharding_mesh()
    if mesh is None:
        return model, optimizer, scaler

    # stage 1/2: shard optimizer state (grads follow by propagation)
    shard_accumulators(optimizer, mesh, axis)

    if level == "p_g_os":
        # stage 3: shard the parameters themselves (gather-on-use by XLA)
        for p in model.parameters():
            p._data = _shard_dim0(p._data, mesh, axis)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os

    from ...framework.io import save

    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
