"""paddle.io — datasets and data loading.

Reference: python/paddle/io/ (Dataset, DataLoader with multiprocess workers at
io/dataloader/worker.py). TPU-native design: workers are threads feeding a
bounded prefetch queue (numpy batches stay on host; device transfer happens at
first op use, letting XLA overlap H2D with compute). The GIL-bound hot loops
— batch collation and image normalize — run in the C++ core
(csrc/prefetch.cpp via io/native.py, ctypes calls release the GIL), so the
thread workers parallelize where it matters; see native.py for the
data_feed.cc analogy.
"""

from __future__ import annotations

import itertools
import math
import queue
import threading

import numpy as np

from ..core import rng as _rng
from ..core.tensor import Tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler",
    "DistributedBatchSampler", "DataLoader", "get_worker_info",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1) < 1e-6:
        n = len(dataset)
        sizes = [int(math.floor(n * l)) for l in lengths]
        rem = n - sum(sizes)
        for i in range(rem):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    assert sum(lengths) == len(dataset)
    perm = np.random.permutation(len(dataset))
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset : offset + l].tolist()))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(
            weights.numpy() if isinstance(weights, Tensor) else weights,
            np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference: python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler — shards indices across data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size

            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            g = np.random.RandomState(self.epoch)
            indices = g.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def _np_collate(batch):
    """Numpy-only mirror of default_collate_fn for process workers: child
    processes must not build Tensors (that would initialize an accelerator
    backend per worker); the parent tensorizes the stacked arrays."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b._data) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return [_np_collate(list(t)) for t in zip(*batch)]
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, str):
        return list(batch)
    return batch


def _tensorize(tree):
    return _tree_map(
        lambda t: Tensor(t) if isinstance(t, np.ndarray) else t, tree)


def _tree_map(fn, tree):
    """Map fn over the non-container leaves of a list/dict batch tree
    (the one walker shared by tensorize/pack/unpack)."""
    if isinstance(tree, list):
        return [_tree_map(fn, t) for t in tree]
    if isinstance(tree, dict):
        return {k: _tree_map(fn, v) for k, v in tree.items()}
    return fn(tree)


def _shm_pack(tree, min_bytes=1 << 20):
    """Move the numpy leaves of a collated batch into ONE shared-memory
    segment (reference use_shared_memory=True: io/dataloader/worker.py
    sends batches via shared memory instead of pickling through the pipe).
    Returns an ("shm", name, spec) token, or ("inline", tree) for small
    batches where the segment setup would cost more than the copy."""
    import multiprocessing.shared_memory as mshm

    arrays = []

    def mark(t):
        if isinstance(t, np.ndarray):
            arrays.append(np.ascontiguousarray(t))
            a = arrays[-1]
            # a.dtype (picklable) — a str() form can't round-trip
            # structured/record dtypes
            return ("__arr__", len(arrays) - 1, a.shape, a.dtype)
        return t

    spec = _tree_map(mark, tree)
    total = sum(a.nbytes for a in arrays)
    if not arrays or total < min_bytes:
        return ("inline", tree)
    seg = mshm.SharedMemory(create=True, size=total)
    off = 0
    offsets = []
    for a in arrays:
        view = np.ndarray(a.shape, a.dtype, buffer=seg.buf, offset=off)
        np.copyto(view, a)
        offsets.append(off)
        off += a.nbytes
    name = seg.name
    seg.close()
    return ("shm", name, spec, offsets)


def _is_arr_marker(t):
    return isinstance(t, tuple) and len(t) == 4 and t[0] == "__arr__"


def _shm_unpack(token):
    kind = token[0]
    if kind == "inline":
        return token[1]
    import multiprocessing.shared_memory as mshm

    _, name, spec, offsets = token
    seg = mshm.SharedMemory(name=name)
    try:
        def restore(t):
            if _is_arr_marker(t):
                _, idx, shape, dtype = t
                view = np.ndarray(shape, dtype, buffer=seg.buf,
                                  offset=offsets[idx])
                return view.copy()  # own the data before the segment dies
            return t

        return _tree_map(restore, spec)
    finally:
        seg.close()
        seg.unlink()


def _shm_discard(token):
    """Unlink a packed batch without reading it (early-exit cleanup)."""
    if token[0] != "shm":
        return
    import multiprocessing.shared_memory as mshm

    try:
        seg = mshm.SharedMemory(name=token[1])
        seg.close()
        seg.unlink()
    except FileNotFoundError:
        pass


_PROC_BUILDER = None  # per-worker-process task state (set by initializer)


def _proc_worker_init(builder):
    """Spawn-process initializer: pins the child to CPU before anything
    imports jax, receives the builder ONCE (one dataset pickle per worker,
    not per batch) and runs worker_init_fn once — the reference's
    once-per-worker contract (io/dataloader/worker.py:_worker_loop)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    global _PROC_BUILDER
    _PROC_BUILDER = builder
    builder._lazy_init()


def _proc_run_batch(indices):
    return _PROC_BUILDER(indices)


class _ProcBatchBuilder:
    """Picklable per-batch task for process workers (reference analog:
    python/paddle/io/dataloader/worker.py:1 _worker_loop — the reference
    forks long-lived workers fed by index queues; spawn + Pool.imap gives
    the same pipeline with order preservation on all platforms)."""

    def __init__(self, dataset, collate_fn, worker_init_fn, num_workers,
                 use_shared_memory=True):
        self.dataset = dataset
        self.collate_fn = collate_fn  # None = numpy default collate
        self.worker_init_fn = worker_init_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self._inited = False

    def _lazy_init(self):
        if self._inited:
            return
        self._inited = True
        import multiprocessing as mp

        ident = mp.current_process()._identity
        wid = (ident[0] - 1) % self.num_workers if ident else 0
        _worker_info.info = _WorkerInfo(wid, self.num_workers, self.dataset)
        if self.worker_init_fn is not None:
            self.worker_init_fn(wid)

    def __call__(self, indices):
        self._lazy_init()
        samples = [self.dataset[i] for i in indices]
        if self.collate_fn is None:
            batch = _np_collate(samples)
            if self.use_shared_memory:
                return _shm_pack(batch)
            return ("inline", batch)
        return ("inline", self.collate_fn(samples))


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor._wrap(jnp.stack([b._data for b in batch]))
    if isinstance(sample, np.ndarray):
        # native parallel-memcpy collator, only when the batch is big
        # enough to amortize thread spawn and ONLY if the library is
        # already loaded (never build on the hot path; DataLoader warms it)
        if len(batch) > 1 and len(batch) * sample.nbytes >= 1 << 20:
            from . import native

            if native.lib_ready() is not None:
                out = native.collate_samples(batch)
                if out is not None:
                    return Tensor(out)
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(t)) for t in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, str):
        return list(batch)
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process_workers=False):
        self.dataset = dataset
        self._custom_collate = collate_fn
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = bool(use_shared_memory)
        # process workers (reference worker.py uses processes always);
        # threads stay the default here because the C++ collate/prefetch
        # core already de-GILs the common path — processes pay pickling but
        # scale arbitrary Python __getitem__/transforms
        self.use_process_workers = bool(use_process_workers)
        from . import native as _native

        _native.warm()  # background-build the C++ core; no blocking here
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle,
                batch_size=batch_size if batch_size is not None else 1,
                drop_last=drop_last)
            if batch_size is None:
                self.batch_sampler = None

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _index_batches(self):
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield [i]
            return
        yield from self.batch_sampler

    def _make_batch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable:
            yield from self._iter_iterable()
            return
        if self.num_workers <= 0:
            for indices in self._index_batches():
                yield self._make_batch(indices)
            return
        if self.use_process_workers:
            yield from self._process_iter()
            return
        yield from self._threaded_iter()

    def _process_iter(self):
        """Process-pool pipeline: spawn workers (pinned to CPU) run
        ``dataset[i]`` + collate off the parent's GIL; ``imap`` preserves
        batch order and a semaphore bounds in-flight batches to
        prefetch_factor * num_workers (buffered_reader backpressure).
        The dataset, collate_fn and worker_init_fn must be picklable —
        the same contract as the reference's process workers
        (python/paddle/io/dataloader/worker.py:1)."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        batches = list(self._index_batches())
        cap = max(1, self.prefetch_factor * self.num_workers)
        sem = threading.Semaphore(cap)
        stop = threading.Event()

        def feed():
            # the pool's task-handler thread runs this generator; it must
            # never block indefinitely, or Pool teardown (early consumer
            # exit, worker exception) would join it forever
            for b in batches:
                while not sem.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                yield b

        builder = _ProcBatchBuilder(self.dataset, self._custom_collate,
                                    self.worker_init_fn, self.num_workers,
                                    use_shared_memory=self.use_shared_memory)
        with ctx.Pool(self.num_workers, initializer=_proc_worker_init,
                      initargs=(builder,)) as pool:
            it = pool.imap(_proc_run_batch, feed(), chunksize=1)
            try:
                for token in it:
                    sem.release()
                    res = _shm_unpack(token)
                    yield (_tensorize(res) if self._custom_collate is None
                           else res)
            finally:
                stop.set()
                sem.release()  # unblock a feed() waiting on backpressure
                # early exit / error: in-flight batches may hold shared-
                # memory segments — drain and unlink so /dev/shm doesn't
                # accumulate across abandoned iterators
                try:
                    for token in it:
                        _shm_discard(token)
                except Exception:
                    pass

    def _threaded_iter(self):
        """Thread-pool prefetch pipeline preserving batch order, with
        bounded in-flight batches (prefetch_factor * num_workers credits):
        workers take a credit before building, the consumer returns it
        after yielding — backpressure so a slow training loop can't let
        the workers buffer the whole epoch (buffered_reader semantics).
        The credit queue is the native C++ ring when built (blocking waits
        happen in C, off the GIL), queue.Queue otherwise."""
        from . import native as _native

        idx_q: queue.Queue = queue.Queue()
        out: dict[int, object] = {}
        done = threading.Event()
        lock = threading.Condition()
        batches = list(self._index_batches())
        for i, b in enumerate(batches):
            idx_q.put((i, b))

        cap = max(1, self.prefetch_factor * self.num_workers)
        ring = None
        if _native.lib_ready() is not None:
            try:
                ring = _native.Ring(cap)
            except RuntimeError:
                ring = None
        if ring is not None:
            for _ in range(cap):
                ring.push(1)
            take_credit = lambda: ring.pop(timeout_ms=200)[0] == 1
            give_credit = lambda: ring.push(1, timeout_ms=0)
        else:
            credits: queue.Queue = queue.Queue()
            for _ in range(cap):
                credits.put(1)

            def take_credit():
                try:
                    credits.get(timeout=0.2)
                    return True
                except queue.Empty:
                    return False

            give_credit = lambda: credits.put(1)

        def worker(wid):
            _worker_info.info = _WorkerInfo(wid, self.num_workers, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(wid)
            while not done.is_set():
                if not take_credit():
                    continue  # backpressure; re-check done
                try:
                    i, indices = idx_q.get_nowait()
                except queue.Empty:
                    give_credit()
                    return
                batch = self._make_batch(indices)
                with lock:
                    out[i] = batch
                    lock.notify_all()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                with lock:
                    while i not in out:
                        lock.wait(timeout=60.0)
                    yield out.pop(i)
                give_credit()
        finally:
            done.set()
            if ring is not None:
                ring.close()

    def __call__(self):
        return iter(self)

    # -- resumable stream passthrough (crash recovery) -------------------
    def _resumable_sampler(self):
        bs = self.batch_sampler
        if bs is None or not hasattr(bs, "state_dict"):
            raise TypeError(
                "this DataLoader's batch sampler is not resumable; use "
                "io.BucketedBatchSampler (or any batch_sampler exposing "
                "state_dict/set_state_dict/advance) to checkpoint the "
                "data stream position")
        return bs

    def state_dict(self):
        """Resume point of the underlying batch sampler (epoch, consumed-
        batch cursor, shuffle seed) — what ``CheckpointManager.save(...,
        sampler=loader)`` persists."""
        return self._resumable_sampler().state_dict()

    def set_state_dict(self, sd):
        self._resumable_sampler().set_state_dict(sd)

    load_state_dict = set_state_dict

    def advance(self, n=1):
        """Report ``n`` consumed batches to the batch sampler (the resume
        cursor counts *trained* batches, never read-ahead)."""
        self._resumable_sampler().advance(n)

    def set_epoch(self, epoch):
        bs = self.batch_sampler
        if bs is not None and hasattr(bs, "set_epoch"):
            bs.set_epoch(epoch)


class SubsetRandomSampler(Sampler):
    """Reference io/sampler.py SubsetRandomSampler."""

    def __init__(self, indices, generator=None):
        if len(indices) == 0:
            raise ValueError(
                "SubsetRandomSampler requires a non-empty indices list")
        self.indices = list(indices)

    def __iter__(self):
        import numpy as np

        from ..core import rng as _rng

        import jax

        seed = int(jax.random.randint(_rng.next_key(), (), 0, 2**31 - 1))
        order = np.random.RandomState(seed).permutation(len(self.indices))
        return iter([self.indices[i] for i in order])

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    """Reference io/dataset.py ConcatDataset: map-style concatenation with
    bisect-based index routing."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ConcatDataset needs at least one dataset")
        import itertools

        self.cumulative_sizes = list(itertools.accumulate(
            len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        import bisect

        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if di == 0 else self.cumulative_sizes[di - 1]
        return self.datasets[di][idx - prev]


__all__ += ["SubsetRandomSampler", "ConcatDataset"]

# shape-bucketed batching (anti-recompile input pipeline; imported last —
# bucketing.py subclasses BatchSampler defined above)
from .bucketing import BucketedBatchSampler, PadToBucket  # noqa: E402,F401

__all__ += ["BucketedBatchSampler", "PadToBucket"]

# double-buffered host->device prefetch (overlap layer; composes with the
# bucketing above: staged batches are padded to bucket shapes off the
# critical path)
from .prefetch import DevicePrefetcher  # noqa: E402,F401

__all__ += ["DevicePrefetcher"]

# fault-tolerant streaming data plane (sharded ingestion over the fleet
# FS surface; resumable through the same sampler-state protocol)
from .streaming import (  # noqa: E402,F401
    ShardManifest, StreamCorruptionError, StreamReadError,
    StreamingDataset, pack_arrays, read_stream_shard, unpack_arrays,
    write_stream_shard)

__all__ += ["StreamingDataset", "ShardManifest", "StreamReadError",
            "StreamCorruptionError", "write_stream_shard",
            "read_stream_shard", "pack_arrays", "unpack_arrays"]


def resolve_resumable(stream):
    """Unwrap pipeline layers (DevicePrefetcher → its source, DataLoader →
    its batch sampler) down to the object that owns the resumable stream
    state, or ``None`` when nothing in the stack supports it. This is how
    ``CheckpointManager`` and ``FusedTrainStep.drive`` accept a prefetcher,
    a loader, or the sampler itself interchangeably as ``sampler=``."""
    obj = stream
    for _ in range(8):  # defensive bound on pathological nesting
        if isinstance(obj, DevicePrefetcher):
            obj = obj.source
        elif isinstance(obj, DataLoader):
            obj = obj.batch_sampler
        else:
            break
    if (obj is not None and hasattr(obj, "state_dict")
            and hasattr(obj, "set_state_dict") and hasattr(obj, "advance")):
        return obj
    return None


__all__ += ["resolve_resumable"]
