"""Fault-tolerant streaming data plane: sharded ingestion over the FS
surface.

Every workload so far trains from in-memory arrays, so none of the
durability guarantees (atomic checkpoints, elastic restarts, bit-exact
resume) extended to the data stream itself. :class:`StreamingDataset` is
the missing tier (ROADMAP item 3): a sharded-by-rank record stream read
through the ``fleet.utils.fs`` surface (``LocalFS`` directly; an
``HDFSClient``-shaped remote FS by download-then-read, the same contract
``need_upload_download()`` already encodes), decoded on a host thread
pool, and consumed through :class:`~paddle_tpu.io.DevicePrefetcher` /
``FusedTrainStep.drive`` like any other batch iterable.

Robustness contract (the reason this module exists):

* **Flaky filesystems.** Every shard open and every frame read goes
  through ``utils.retry.retry_os`` (the one backoff shape the checkpoint
  lifecycle already uses) and carries the fault-injection sites
  ``io.stream.open`` / ``io.stream.read``. A transient NFS/FUSE hiccup is
  retried invisibly; budget exhaustion raises a typed
  :class:`StreamReadError` instead of a raw OSError ten frames deep.
* **Corrupt records.** Each record is length-framed with a CRC32
  (``write_stream_shard`` writes shards atomically so a killed writer can
  never publish a torn shard). A CRC mismatch, a decode failure, or the
  armed ``io.stream.corrupt`` site *quarantines* the record: it is
  skipped, counted in ``io_records_quarantined_total``, and charged
  against a per-epoch skip budget (``max_skips_per_epoch``, a leaky
  bucket mirroring the launcher's ``RestartBudget`` discipline). Budget
  exhaustion raises a typed :class:`StreamCorruptionError` — a rotten
  shard degrades loudly instead of silently starving training. A torn
  tail (truncated final record) or an unparseable frame structure ends
  the shard through the same quarantine accounting.
* **Elastic restarts.** The dataset implements the resumable-stream
  protocol (``state_dict`` / ``set_state_dict`` / ``advance``) the PR-4
  supervision stack already persists through ``CheckpointManager``: the
  cursor counts *consumed* batches (``advance`` is called by the training
  driver, never the read-ahead), so a kill -9 / preempt / hang mid-epoch
  resumes the exact remaining record sequence bit-for-bit
  (``scripts/chaos_train.py --drill stream`` is the acceptance drill).
  The state embeds a fingerprint of the shard manifest — resuming against
  a changed shard set fails typed instead of replaying the wrong data.
  On a *world-size change*, :meth:`StreamingDataset.set_group_state`
  re-partitions only the unconsumed work items across the new ranks while
  preserving every in-progress shard's byte cursor
  (:func:`rebalance_states` is the pure re-partition function).

Determinism: shard order comes from the sorted manifest (``LocalFS`` and
``HDFSClient`` listings are sorted — readdir order must never pick the
shard→rank assignment), records are consumed strictly in stream order,
and corrupt records are corrupt *on disk*, so a resumed pass quarantines
the same records at the same positions.
"""

from __future__ import annotations

import collections
import hashlib
import io as _pyio
import itertools
import os
import struct
import threading
import zlib

import numpy as np

from ..observability import metrics as _obs_metrics
from ..utils import fault_injection
from ..utils.retry import atomic_write, retry_os

__all__ = [
    "MAGIC", "StreamReadError", "StreamCorruptionError", "ShardManifest",
    "StreamingDataset", "write_stream_shard", "read_stream_shard",
    "pack_arrays", "unpack_arrays", "rebalance_states",
]

# shard container format: 8-byte magic, then length-framed records
# [u32 payload_len][u32 crc32(payload)][payload]; all little-endian.
MAGIC = b"PDSTRM01"
_FRAME = struct.Struct("<II")
# a frame length beyond this is structural corruption, not a big record:
# the stream cannot re-synchronize past a lying length field, so the rest
# of the shard is quarantined as one torn region
_MAX_RECORD_BYTES = 1 << 30

# streaming-plane telemetry (ISSUE 13): instance-labeled like the
# prefetcher's series so two concurrent streams never merge
_C_RECORDS = _obs_metrics.counter(
    "io_stream_records_total",
    "records decoded and delivered by StreamingDataset (quarantined "
    "records are NOT counted here)")
_C_BYTES = _obs_metrics.counter(
    "io_stream_bytes_total",
    "payload bytes read from stream shards (including payloads later "
    "quarantined — the read happened)")
_C_RETRIES = _obs_metrics.counter(
    "io_stream_retries_total",
    "shard open/read attempt failures (transient ones are retried by "
    "utils.retry; the final failure surfaces as StreamReadError)")
_C_QUARANTINED = _obs_metrics.counter(
    "io_records_quarantined_total",
    "corrupt/torn records skipped under the per-epoch skip budget "
    "(CRC mismatch, decode failure, torn tail, io.stream.corrupt)")


class StreamReadError(RuntimeError):
    """A shard open/read kept failing past the transient-retry budget
    (``FLAGS_ckpt_save_retries`` attempts with backoff — the shared
    durability retry shape). The underlying OSError is chained; the
    shard path and byte offset identify the failing region."""

    def __init__(self, msg, path=None, offset=None):
        super().__init__(msg)
        self.path = path
        self.offset = offset


class StreamCorruptionError(RuntimeError):
    """The per-epoch quarantine skip budget is exhausted: more corrupt /
    torn records than ``max_skips_per_epoch`` allows. Carries the
    positions of the quarantined records seen this epoch so the rotten
    shard(s) can be identified without re-reading the stream."""

    def __init__(self, msg, quarantined=None):
        super().__init__(msg)
        self.quarantined = list(quarantined or [])


# ---------------------------------------------------------------------------
# record payload helpers
# ---------------------------------------------------------------------------

def pack_arrays(*arrays):
    """Serialize a tuple of numpy arrays into one record payload (npz
    container, no pickle). The inverse is :func:`unpack_arrays`."""
    buf = _pyio.BytesIO()
    np.savez(buf, *[np.asarray(a) for a in arrays])
    return buf.getvalue()


def unpack_arrays(payload):
    """Default ``decode_fn``: the tuple of arrays :func:`pack_arrays`
    wrote, in order. Raises on malformed payloads (the quarantine path
    catches it)."""
    with np.load(_pyio.BytesIO(payload), allow_pickle=False) as z:
        return tuple(z[k] for k in sorted(z.files,
                                          key=lambda n: int(n[4:])))


def write_stream_shard(path, records, encode_fn=None, fs=None):
    """Write one shard of ``records`` atomically (tmp → fsync → rename via
    ``utils.retry.atomic_write``): a killed writer can never leave a torn
    shard visible — the destination either holds the complete shard or
    does not exist. ``records`` is an iterable of payloads (bytes), or of
    anything ``encode_fn`` turns into bytes (tuples of arrays pass
    through :func:`pack_arrays` when ``encode_fn`` is omitted). With a
    remote ``fs`` (``need_upload_download()``), the shard is staged
    locally and uploaded. Returns the record count."""
    n = 0

    def body(f):
        nonlocal n
        n = 0
        f.write(MAGIC)
        for rec in records:
            if not isinstance(rec, (bytes, bytearray)):
                rec = (encode_fn(rec) if encode_fn is not None
                       else pack_arrays(*rec) if isinstance(rec, tuple)
                       else pack_arrays(rec))
            f.write(_FRAME.pack(len(rec), zlib.crc32(rec)))
            f.write(rec)
            n += 1

    if fs is not None and fs.need_upload_download():
        import shutil
        import tempfile

        # stage in a temp dir, never the cwd (launcher-managed jobs
        # often run from read-only working directories)
        stage = tempfile.mkdtemp(prefix="pdstream_stage_")
        try:
            local = os.path.join(stage, os.path.basename(path))
            atomic_write(local, body)
            fs.upload(local, path)
        finally:
            shutil.rmtree(stage, ignore_errors=True)
    else:
        atomic_write(path, body)
    return n


def read_stream_shard(path, decode_fn=None):
    """Plain non-resilient reader (tests / offline inspection): every
    decoded record of one shard, raising on any corruption."""
    decode_fn = decode_fn or unpack_arrays
    out = []
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise StreamCorruptionError(f"{path}: bad shard magic")
        while True:
            hdr = f.read(_FRAME.size)
            if not hdr:
                return out
            if len(hdr) < _FRAME.size:
                raise StreamCorruptionError(f"{path}: torn frame header")
            length, crc = _FRAME.unpack(hdr)
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                raise StreamCorruptionError(f"{path}: corrupt record")
            out.append(decode_fn(payload))


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------

class ShardManifest:
    """The ordered shard list one stream reads, plus its fingerprint.

    Built from a directory (``build`` — listed through the FS surface,
    which returns *sorted* names, so the shard→rank assignment can never
    depend on readdir order) or from explicit paths (``from_paths``).
    ``fingerprint()`` digests the shard *names* — the identity a resume
    must match; a renamed/added/removed shard changes it and the restore
    fails typed instead of replaying the wrong data."""

    def __init__(self, paths):
        paths = [str(p) for p in paths]
        if not paths:
            raise ValueError("ShardManifest needs at least one shard")
        self.paths = tuple(paths)

    @classmethod
    def build(cls, root, fs=None, suffix=".pdstream"):
        if fs is None:
            from ..distributed.fleet.utils.fs import LocalFS

            fs = LocalFS()
        _dirs, files = fs.ls_dir(root)
        names = sorted(f for f in files if f.endswith(suffix))
        if not names:
            raise FileNotFoundError(
                f"no *{suffix} shards under {root!r}")
        sep = "" if str(root).endswith("/") else "/"
        return cls([f"{root}{sep}{name}" for name in names])

    @classmethod
    def from_paths(cls, paths):
        return cls(sorted(str(p) for p in paths))

    def __len__(self):
        return len(self.paths)

    def fingerprint(self):
        h = hashlib.sha1()
        for p in self.paths:
            h.update(os.path.basename(p).encode())
            h.update(b"\0")
        return f"{len(self.paths)}:{h.hexdigest()[:16]}"


# ---------------------------------------------------------------------------
# rebalance (elastic world-size change)
# ---------------------------------------------------------------------------

def _default_work(num_shards, rank, world_size):
    """The fresh-epoch work list of ``rank``: round-robin shard
    assignment, every item starting at the first record."""
    return [[i, len(MAGIC)] for i in range(num_shards)
            if i % world_size == rank]


def rebalance_states(states, new_world_size):
    """Re-partition the *remaining* work of an old world's per-rank
    states across ``new_world_size`` ranks. Fully-consumed shards stay
    consumed (never replayed); the in-progress shard of each old rank
    keeps its exact byte cursor; only unconsumed work moves. Returns one
    state dict per new rank.

    Deterministic: remaining items are pooled sorted by shard index and
    dealt round-robin, so every rank of the new world computes the same
    partition from the same checkpoint. The per-epoch quarantine skip
    budget restarts clean for the new ranks (their skip positions are no
    longer comparable to any single old rank's count)."""
    if not states:
        raise ValueError("rebalance_states needs at least one rank state")
    fp = states[0]["manifest"]
    epoch = states[0]["epoch"]
    for sd in states:
        if sd["manifest"] != fp:
            raise ValueError(
                "rebalance across DIFFERENT shard manifests: "
                f"{sd['manifest']} vs {fp}")
        if sd["epoch"] != epoch:
            raise ValueError(
                f"rebalance across different epochs: rank "
                f"{sd.get('rank')} is at epoch {sd['epoch']}, rank "
                f"{states[0].get('rank')} at {epoch}. With per-rank "
                "shard counts uneven (shards not a multiple of the old "
                "world size) ranks finish epochs at different times, "
                "and exactly-once re-partitioning is undefined across "
                "epoch boundaries — resume at the original world size, "
                "or size the shard set as a multiple of the world")
    remaining = []
    for sd in states:
        if sd.get("exhausted"):
            continue
        work, k, off = sd["work"], sd["cursor_k"], sd["cursor_offset"]
        for j in range(k, len(work)):
            shard, start = work[j]
            # a None cursor offset means "the item's own start" (fresh
            # item / fresh epoch) — only a mid-item cursor overrides it
            use = off if (j == k and off is not None) else start
            remaining.append([int(shard), int(use)])
    remaining.sort()
    base = dict(states[0])
    out = []
    for r in range(int(new_world_size)):
        sd = dict(base)
        sd.update({
            "rank": r, "world_size": int(new_world_size),
            "work": [list(it) for it in remaining[r::new_world_size]],
            "cursor_k": 0, "cursor_offset": None, "batches_consumed": 0,
            "skips": 0, "exhausted": not remaining[r::new_world_size],
        })
        out.append(sd)
    return out


# ---------------------------------------------------------------------------
# the streaming dataset
# ---------------------------------------------------------------------------

class StreamingDataset:
    """Sharded, resumable, corruption-quarantining record stream yielding
    collated batches.

    Arguments:
        shards: a directory of ``*.pdstream`` shards, a
            :class:`ShardManifest`, or an explicit list of shard paths.
        batch_size: records per yielded batch.
        fs: the filesystem client (default ``LocalFS``). A remote FS
            (``need_upload_download()``) has each shard downloaded to a
            local cache before reading — the ``HDFSClient`` shape.
        decode_fn: payload bytes → sample (default
            :func:`unpack_arrays`). Runs on the decode thread pool; a
            raising decode quarantines the record.
        collate_fn: list of samples → batch (default: the numpy
            collation the DataLoader's process workers use). Pass
            ``io.PadToBucket(boundaries, as_tensor=False)`` for the
            varlen→bucket pipeline; the batch then pads up to the PR-1
            shape buckets downstream in ``DevicePrefetcher``.
        rank / world_size: shard assignment (defaults:
            ``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM``). Rank ``r``
            owns shards ``r, r+world, ...`` of the sorted manifest.
        num_workers: decode thread-pool width (0 = inline decode).
        max_skips_per_epoch: quarantine skip budget per epoch. The
            default 0 raises :class:`StreamCorruptionError` at the FIRST
            corrupt record — skipping data is opt-in, never silent.
        drop_last: drop the trailing sub-``batch_size`` batch.
        name: metrics instance label (stable across restarts for
            continuous series; default auto-numbered).

    The resumable protocol matches ``BucketedBatchSampler``: the consumer
    calls ``advance(1)`` per *trained* batch, ``state_dict()`` returns
    the committed cursor (next unread work item + byte offset), and a
    restored state makes the next ``__iter__`` replay the exact remaining
    batch sequence. Read-ahead (DevicePrefetcher staging, the decode
    pool) never moves the cursor.
    """

    _instance_ids = itertools.count(1)

    def __init__(self, shards, batch_size, fs=None, decode_fn=None,
                 collate_fn=None, rank=None, world_size=None,
                 num_workers=2, max_skips_per_epoch=0, drop_last=False,
                 name=None, cache_dir=None, retry_base_delay_s=0.01):
        if fs is None:
            from ..distributed.fleet.utils.fs import LocalFS

            fs = LocalFS()
        self._fs = fs
        if isinstance(shards, ShardManifest):
            self.manifest = shards
        elif isinstance(shards, (list, tuple)):
            self.manifest = ShardManifest.from_paths(shards)
        else:
            self.manifest = ShardManifest.build(shards, fs=fs)
        if int(batch_size) < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.batch_size = int(batch_size)
        self.decode_fn = decode_fn or unpack_arrays
        self.collate_fn = collate_fn
        self.drop_last = bool(drop_last)
        self.num_workers = max(0, int(num_workers))
        if max_skips_per_epoch is not None and int(max_skips_per_epoch) < 0:
            raise ValueError("max_skips_per_epoch must be >= 0 (or None "
                             "for unlimited)")
        self.max_skips_per_epoch = (None if max_skips_per_epoch is None
                                    else int(max_skips_per_epoch))
        if rank is None:
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        if world_size is None:
            world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        if not 0 <= int(rank) < int(world_size):
            raise ValueError(
                f"rank {rank} out of range for world_size {world_size}")
        if int(world_size) > len(self.manifest):
            # shard-granular parallelism: a world larger than the shard
            # set would leave ranks with an EMPTY work list silently
            # yielding nothing every epoch — fail loudly instead
            raise ValueError(
                f"world_size {world_size} exceeds the {len(self.manifest)}"
                f"-shard manifest: rank(s) >= {len(self.manifest)} would "
                "train NOTHING. Write at least world_size shards (smaller "
                "shards parallelize ingestion too)")
        self._rank = int(rank)
        self._world = int(world_size)
        self._cache_dir = cache_dir
        # first-retry sleep for transient open/read failures. The shared
        # retry budget/backoff SHAPE stays (FLAGS_ckpt_save_retries
        # attempts, exponential, capped) — but an ingest retry sits on
        # the staging critical path, where a checkpoint-write-sized
        # backoff (10ms) would stall the prefetch queue, so the base is
        # tunable per stream
        self._retry_base_delay_s = float(retry_base_delay_s)
        uid = next(StreamingDataset._instance_ids)
        self._metrics_label = name or f"streaming_dataset#{uid}"
        # committed (advance()-driven) stream position — what state_dict
        # persists. work: this epoch's ordered [shard_idx, start_offset]
        # items; cursor_k/cursor_offset: the next unread record
        # (cursor_offset None = the item's own start offset).
        self._epoch = 0
        self._work = _default_work(len(self.manifest), self._rank,
                                   self._world)
        self._cursor_k = 0
        self._cursor_offset = None
        self._batches_consumed = 0
        self._skips = 0          # committed quarantines this epoch
        self._exhausted = False
        self._quarantine_log = []   # (shard_path, offset, reason)
        # producer→consumer handoff: one entry per yielded batch, popped
        # by advance() on the training thread while the generator appends
        # on the prefetcher's transfer thread. RLock: cursor mutations
        # (advance, epoch rolls, state restore) hold it end to end, and
        # an advance that rolls the epoch re-enters through _reset_epoch
        self._produced = collections.deque()
        self._lock = threading.RLock()
        # iteration generation: bumped by every __iter__, captured by the
        # generator it returns. A SUPERSEDED generator (a prefetcher
        # transfer thread whose join timed out while blocked in a slow
        # read, finishing one last batch after the stream was re-opened)
        # must never append handoff entries, rewrite them, or roll the
        # epoch — a phantom entry would make advance() commit a stale
        # cursor and silently break bit-exact resume
        self._iter_gen = 0
        # positions already charged to the quarantine telemetry: a
        # discarded-read-ahead re-iteration (DevicePrefetcher reset)
        # re-encounters the same on-disk corruption and must not double-
        # count it in stats/io_records_quarantined_total/the log
        self._quarantine_seen = set()
        self._stats = {"batches": 0, "records": 0, "bytes": 0,
                       "quarantined": 0, "retries": 0, "epochs": 0}

    # -- telemetry -------------------------------------------------------
    def stats(self):
        """Instance counters (the same numbers land in the registry under
        ``io_stream_*`` / ``io_records_quarantined_total``)."""
        d = dict(self._stats)
        d["skip_budget"] = self.max_skips_per_epoch
        d["quarantine_log"] = list(self._quarantine_log)
        return d

    def close(self):
        """Remove this instance's registry series (the per-object label
        must not outlive the object's working life — the DevicePrefetcher
        discipline). The dataset stays usable; the next read re-creates
        the series."""
        for m in (_C_RECORDS, _C_BYTES, _C_RETRIES, _C_QUARANTINED):
            m.remove(instance=self._metrics_label)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- resumable-stream protocol (crash recovery) ----------------------
    def set_epoch(self, epoch):
        """Enter epoch ``epoch`` fresh (cursor reset, default shard
        assignment for the CURRENT world, skip budget re-armed) — unless
        it is the epoch a checkpoint just restored, which keeps its
        place. The ``BucketedBatchSampler.set_epoch`` contract."""
        epoch = int(epoch)
        if epoch != self._epoch:
            self._reset_epoch(epoch)

    def _reset_epoch(self, epoch):
        with self._lock:
            self._epoch = int(epoch)
            self._work = _default_work(len(self.manifest), self._rank,
                                       self._world)
            self._cursor_k = 0
            self._cursor_offset = None
            self._batches_consumed = 0
            self._skips = 0
            self._exhausted = False
            self._quarantine_log = []
            self._quarantine_seen = set()
            self._produced.clear()

    def advance(self, n=1):
        """Commit ``n`` more *consumed* (trained) batches: the cursor
        moves to the position after the last one. Called by the training
        driver — read-ahead layers never touch it.

        Consuming the LAST batch of the epoch rolls the cursor into the
        next epoch immediately (the ``BucketedBatchSampler.advance``
        contract): a checkpoint written exactly at an epoch boundary
        records ``(epoch+1, start)`` — never an ambiguous "epoch N,
        done" state that a resumed epoch loop would train twice."""
        for _ in range(int(n)):
            # the whole commit is one critical section: the generator's
            # end-of-epoch roll must never interleave with a half-applied
            # cursor update (stale fields overwriting a fresh reset)
            with self._lock:
                if not self._produced:
                    raise RuntimeError(
                        "advance() past the produced stream: the driver "
                        "reported more consumed batches than were yielded")
                k, off, skips, end = self._produced.popleft()
                self._cursor_k = k
                self._cursor_offset = off
                self._skips = skips
                self._batches_consumed += 1
                if end:
                    self._roll_epoch()

    def _roll_epoch(self):
        self._reset_epoch(self._epoch + 1)
        self._stats["epochs"] += 1

    def state_dict(self):
        """The committed resume point: epoch, this epoch's work list and
        cursor, consumed-batch count, quarantine count — plus the
        manifest fingerprint and stream geometry, so a restore into a
        different pipeline fails loudly."""
        with self._lock:
            return self._state_dict_locked()

    def _state_dict_locked(self):
        return {
            "stream": 1,
            "epoch": self._epoch,
            "work": [list(it) for it in self._work],
            "cursor_k": int(self._cursor_k),
            "cursor_offset": (None if self._cursor_offset is None
                              else int(self._cursor_offset)),
            "batches_consumed": int(self._batches_consumed),
            "skips": int(self._skips),
            "exhausted": bool(self._exhausted),
            "manifest": self.manifest.fingerprint(),
            "num_shards": len(self.manifest),
            "batch_size": self.batch_size,
            "drop_last": self.drop_last,
            "world_size": self._world,
            "rank": self._rank,
        }

    def _check_fingerprint(self, sd):
        for key, have in (("manifest", self.manifest.fingerprint()),
                          ("num_shards", len(self.manifest)),
                          ("batch_size", self.batch_size),
                          ("drop_last", self.drop_last)):
            if key in sd and sd[key] != have:
                raise ValueError(
                    f"stream state mismatch on {key!r}: checkpoint has "
                    f"{sd[key]!r}, this stream has {have!r} — resuming "
                    "would replay a different record sequence")

    def set_state_dict(self, sd):
        if "stream" not in sd:
            raise ValueError(
                "not a StreamingDataset state (restoring a different "
                "sampler's checkpoint into a streaming pipeline?)")
        self._check_fingerprint(sd)
        if int(sd.get("world_size", self._world)) != self._world:
            raise ValueError(
                f"stream state was written under world_size="
                f"{sd.get('world_size')} but this stream runs "
                f"world_size={self._world}; use set_group_state with "
                "every rank's state to re-balance the unconsumed shards")
        with self._lock:
            self._epoch = int(sd["epoch"])
            self._work = [list(it) for it in sd["work"]]
            self._cursor_k = int(sd["cursor_k"])
            self._cursor_offset = (None if sd["cursor_offset"] is None
                                   else int(sd["cursor_offset"]))
            self._batches_consumed = int(sd.get("batches_consumed", 0))
            self._skips = int(sd.get("skips", 0))
            self._exhausted = bool(sd.get("exhausted", False))
            self._quarantine_log = []
            self._quarantine_seen = set()
            self._produced.clear()

    load_state_dict = set_state_dict

    def set_group_state(self, states):
        """Restore from EVERY old rank's state (what
        ``CheckpointManager.auto_resume`` hands over when the checkpoint
        carries per-rank sampler files). Same world: this rank's own
        state restores bit-exactly. Different world (elastic restart):
        the unconsumed work is re-partitioned via
        :func:`rebalance_states` — consumed shards stay consumed,
        in-progress byte cursors are preserved."""
        states = sorted(states, key=lambda s: int(s.get("rank", 0)))
        for sd in states:
            self._check_fingerprint(sd)
        # exact-match first: my own (rank, world) state restores
        # bit-exactly — this also covers per-rank PRIVATE checkpoint
        # directories (coordination-free data-sharded workers), where
        # each manager holds exactly one rank's cursor file
        for sd in states:
            if (int(sd.get("rank", -1)) == self._rank
                    and int(sd.get("world_size", -1)) == self._world):
                self.set_state_dict(sd)
                return
        old_world = int(states[0].get("world_size", len(states)))
        if len(states) != old_world or \
                sorted(int(s.get("rank", -1)) for s in states) \
                != list(range(old_world)):
            raise ValueError(
                f"set_group_state needs either this rank's own "
                f"(rank={self._rank}, world_size={self._world}) state or "
                f"the COMPLETE old world's state set to re-balance; got "
                f"{len(states)} state(s) recorded under world_size="
                f"{old_world} — a partial set cannot be re-partitioned "
                "without losing records")
        new_states = rebalance_states(states, self._world)
        self.set_state_dict(new_states[self._rank])

    # -- resilient IO ----------------------------------------------------
    def _local_path(self, path):
        """LocalFS paths read in place; a remote FS downloads the shard
        to a local cache first (the HDFSClient contract — remote reads
        are whole-object). The cache key digests the FULL remote path:
        two jobs whose shards share a basename (``.../jobA/shard-00`` vs
        ``.../jobB/shard-00``) must never read each other's cache
        entries. Shards are immutable by convention (the writer
        publishes atomically and re-publishing under the same name
        would also defeat the manifest fingerprint), so a cached copy
        is served without re-download."""
        if not self._fs.need_upload_download():
            return path
        import tempfile

        cache = self._cache_dir or os.path.join(
            tempfile.gettempdir(), f"pdstream_cache_{os.getuid()}")
        os.makedirs(cache, exist_ok=True)
        digest = hashlib.sha1(str(path).encode()).hexdigest()[:12]
        local = os.path.join(cache,
                             f"{digest}-{os.path.basename(path)}")
        if not os.path.exists(local):
            # atomic cache fill: download lands under a tmp name and
            # publishes with one rename — a process killed mid-download
            # (exactly this PR's fault model) can never poison the cache
            # with a torn shard the exists-check would then serve
            # forever, and concurrent ranks sharing the cache dir race
            # benignly (last replace wins, same bytes)
            tmp = f"{local}.dl.{os.getpid()}"
            try:
                self._fs.download(path, tmp)
                os.replace(tmp, local)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        return local

    def _retry_io(self, attempt, what, path, offset=None):
        """The one retry harness both IO sites share: each attempt
        failure bumps the retry telemetry, transient OSErrors ride the
        shared backoff (per-stream first-retry delay), and budget
        exhaustion wraps into typed :class:`StreamReadError` carrying
        the failing shard path (+ byte offset for reads)."""
        def counted():
            try:
                return attempt()
            except OSError:
                self._stats["retries"] += 1
                _C_RETRIES.inc(instance=self._metrics_label)
                raise

        try:
            return retry_os(counted, base_delay=self._retry_base_delay_s)
        except OSError as e:
            raise StreamReadError(
                f"{what} kept failing after retries: {e}",
                path=path, offset=offset) from e

    def _open(self, path):
        def attempt():
            fault_injection.fire("io.stream.open")
            return open(self._local_path(path), "rb")

        return self._retry_io(
            attempt, f"open of stream shard {path!r}", path)

    def _read_at(self, f, path, offset, n):
        """Read exactly up to ``n`` bytes at ``offset``, re-seeking on
        every retry so a partially-consumed flaky read can't skew the
        frame. Short data near EOF is returned short (torn-tail handling
        is the caller's)."""
        def attempt():
            f.seek(offset)
            fault_injection.fire("io.stream.read")
            return f.read(n)

        return self._retry_io(
            attempt, f"read of {n} bytes at {path!r}:{offset}", path,
            offset=offset)

    # -- iteration -------------------------------------------------------
    def _quarantine(self, skips, path, offset, reason, gen):
        """Charge one quarantined record against the epoch skip budget;
        raises typed StreamCorruptionError past the budget. Telemetry is
        idempotent per (shard, offset) within an epoch — a re-iteration
        from the committed cursor (discarded read-ahead) re-encounters
        the same on-disk corruption without inflating the counters or
        duplicating log entries — and a SUPERSEDED generator charges
        nothing shared (its budget raise still fires, harmlessly, into
        its dead consumer)."""
        skips += 1
        key = (path, int(offset))
        with self._lock:
            if gen == self._iter_gen and key not in self._quarantine_seen:
                self._quarantine_seen.add(key)
                self._stats["quarantined"] += 1
                _C_QUARANTINED.inc(instance=self._metrics_label)
                self._quarantine_log.append((path, int(offset), reason))
        if (self.max_skips_per_epoch is not None
                and skips > self.max_skips_per_epoch):
            raise StreamCorruptionError(
                f"quarantine skip budget exhausted: {skips} corrupt/torn "
                f"records this epoch > max_skips_per_epoch="
                f"{self.max_skips_per_epoch} (latest: {reason} at "
                f"{path!r}:{offset})", quarantined=self._quarantine_log)
        return skips

    def _frames(self, work, k, start_k, start_offset):
        """Raw frames of work item ``k`` from the committed/start offset:
        yields ("rec", payload, next_offset, record_offset) for intact
        frames and ("corrupt", (path, offset, reason), next_offset_or_end,
        record_offset) for CRC-bad / torn / structurally-broken regions.
        Never decodes (that's the pool's). ``work``/``start_k``/
        ``start_offset`` are the iteration's own captured snapshot —
        instance state would let a superseded generator read the NEW
        iteration's work list (wrong shards, or an IndexError after a
        rebalance shrank it)."""
        shard_idx, start = work[k]
        path = self.manifest.paths[shard_idx]
        offset = int(start)
        if k == start_k and start_offset is not None:
            offset = int(start_offset)
        f = self._open(path)
        with f:
            if offset <= len(MAGIC):
                magic = self._read_at(f, path, 0, len(MAGIC))
                if magic != MAGIC:
                    yield ("corrupt", (path, 0, "bad shard magic"),
                           None, 0)
                    return
                offset = len(MAGIC)
            while True:
                hdr = self._read_at(f, path, offset, _FRAME.size)
                if not hdr:
                    return
                if len(hdr) < _FRAME.size:
                    yield ("corrupt", (path, offset, "torn frame header"),
                           None, offset)
                    return
                length, crc = _FRAME.unpack(hdr)
                if length > _MAX_RECORD_BYTES:
                    # a lying length field: no way to find the next frame
                    # boundary — the rest of the shard is one torn region
                    yield ("corrupt",
                           (path, offset, "unparseable frame length"),
                           None, offset)
                    return
                payload = self._read_at(f, path, offset + _FRAME.size,
                                        length)
                next_off = offset + _FRAME.size + length
                if len(payload) < length:
                    yield ("corrupt", (path, offset, "torn record tail"),
                           None, offset)
                    return
                self._stats["bytes"] += length
                _C_BYTES.inc(length, instance=self._metrics_label)
                if zlib.crc32(payload) != crc:
                    yield ("corrupt", (path, offset, "crc mismatch"),
                           next_off, offset)
                else:
                    yield ("rec", payload, next_off, offset)
                offset = next_off

    def _decoded(self, work, start_k, start_offset):
        """(sample_or_corruption, cursor) stream across the iteration's
        captured work snapshot, with decode fanned out on the host
        thread pool (bounded in-flight window, strict output order).
        ``cursor`` is the committed position IF the stream is consumed
        through this record: (next_work_item, next_offset)."""
        from concurrent.futures import ThreadPoolExecutor

        def decode(payload):
            fault_injection.fire("io.stream.corrupt")
            return self.decode_fn(payload)

        def items():
            for k in range(start_k, len(work)):
                shard_idx, _ = work[k]
                path = self.manifest.paths[shard_idx]
                for kind, payload, next_off, rec_off in self._frames(
                        work, k, start_k, start_offset):
                    if next_off is None:     # shard ends here
                        cursor = (k + 1, None)
                    else:
                        cursor = (k, next_off)
                    yield (kind, payload, cursor, path, rec_off)

        if self.num_workers <= 0:
            for kind, payload, cursor, path, rec_off in items():
                if kind == "rec":
                    try:
                        sample = decode(payload)
                    except StreamReadError:
                        raise
                    except Exception as e:
                        yield (("corrupt",
                                (path, rec_off,
                                 f"decode failed: {e!r}")), cursor)
                        continue
                    yield (("rec", sample), cursor)
                else:
                    yield (("corrupt", payload), cursor)
            return
        window = collections.deque()
        with ThreadPoolExecutor(
                max_workers=self.num_workers,
                thread_name_prefix=f"{self._metrics_label}-decode") as pool:
            def drain(entry):
                (kind, obj, cursor, path, rec_off) = entry
                if kind != "rec":
                    return (("corrupt", obj), cursor)
                try:
                    return (("rec", obj.result()), cursor)
                except StreamReadError:
                    # an IO-performing decode_fn that exhausted the
                    # retry budget is an UNREADABLE filesystem, not
                    # on-disk corruption — it must fail typed like the
                    # inline path, never be skipped past via the budget
                    raise
                except Exception as e:
                    return (("corrupt",
                             (path, rec_off,
                              f"decode failed: {e!r}")), cursor)

            for kind, payload, cursor, path, rec_off in items():
                if kind == "rec":
                    window.append((kind, pool.submit(decode, payload),
                                   cursor, path, rec_off))
                else:
                    window.append((kind, payload, cursor, path, rec_off))
                if len(window) >= self.num_workers * 2:
                    yield drain(window.popleft())
            while window:
                yield drain(window.popleft())

    def __iter__(self):
        # a fully-consumed epoch rolls over automatically (the
        # BucketedBatchSampler contract), so resume-armed epoch loops
        # that never call set_epoch still make progress, and a
        # checkpoint taken exactly at an epoch boundary resumes into the
        # NEXT epoch instead of an empty pass
        if self._exhausted or self._cursor_k >= len(self._work):
            self._roll_epoch()
        with self._lock:
            # read-ahead produced but never advanced is DISCARDED: a new
            # pass restarts from the committed cursor, so nothing is
            # consumed twice (the DevicePrefetcher reset contract) — and
            # the generation bump invalidates any superseded generator
            # still finishing its last batch on a stale transfer thread
            self._produced.clear()
            self._iter_gen += 1
            gen = self._iter_gen
            start_k = self._cursor_k
            start_offset = self._cursor_offset
            # full snapshot: a superseded generator must keep reading
            # ITS epoch's work list even after a restore/rebalance
            # swapped the instance's
            work = [list(it) for it in self._work]
        return self._generate(gen, work, start_k, start_offset)

    def _generate(self, gen, work, start_k, start_offset):
        from . import _np_collate

        skips = self._skips
        buf = []
        last_cursor = None
        for (kind, obj), cursor in self._decoded(work, start_k,
                                                 start_offset):
            if kind == "corrupt":
                path, off, reason = obj
                skips = self._quarantine(skips, path, off, reason, gen)
            else:
                buf.append(obj)
            last_cursor = cursor
            if len(buf) >= self.batch_size:
                samples, buf = buf, []
                yield self._emit(samples, cursor, skips, _np_collate,
                                 end=False, gen=gen)
        if buf and not self.drop_last:
            yield self._emit(buf, (len(work), None), skips,
                             _np_collate, end=True, gen=gen)
        else:
            # the last yielded batch closes the epoch: mark its handoff
            # entry so its advance() rolls the epoch — or roll right here
            # when every yielded batch was already consumed. One critical
            # section: advance() holds the same lock across its pop AND
            # cursor write, so the roll can never interleave with a
            # half-applied commit. A superseded generation owns none of
            # this state and must touch nothing.
            with self._lock:
                if gen != self._iter_gen:
                    return
                if self._produced:
                    k, off, sk, _ = self._produced[-1]
                    self._produced[-1] = (k, off, sk, True)
                elif last_cursor is not None or self._batches_consumed:
                    self._roll_epoch()

    def _emit(self, samples, cursor, skips, np_collate, end, gen):
        k, off = cursor
        with self._lock:
            # a superseded generator's batch goes nowhere (its consumer
            # is a dead prefetcher thread) — recording its cursor would
            # hand advance() a phantom commit point, and its records/
            # batches were never DELIVERED, so the delivery telemetry
            # stays behind the same generation check (bytes stay counted
            # at read time: that IO really happened)
            if gen == self._iter_gen:
                self._produced.append(
                    (k, off if off is not None else None, skips, end))
                self._stats["batches"] += 1
                self._stats["records"] += len(samples)
                _C_RECORDS.inc(len(samples), instance=self._metrics_label)
        collate = self.collate_fn or np_collate
        return collate(samples)

    def __call__(self):
        return iter(self)
