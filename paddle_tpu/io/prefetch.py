"""Device prefetcher: double-buffered host→device transfer.

The DataLoader delivers host batches and the device transfer happens at
dispatch time, so a plain training loop pays host batch production + H2D
latency *serially* with every step (PERF.md: ~8–15 ms per host round-trip
over the axon tunnel, ~0.8 ms per dispatch). ``DevicePrefetcher`` is the
buffered-reader analog of the reference's
``paddle/fluid/operators/reader/buffered_reader.cc`` (which stages batches
onto the device on a side stream): a transfer thread pulls batch N+1 from
the source iterator, pads it to the registered shape buckets ON THE HOST
THREAD (so bucketing costs nothing on the critical path and the staged
shapes hit the same compiled executables — zero extra compiles), and
starts the device transfer with ``jax.device_put`` (async: the copy
overlaps the consumer's compute on batch N). A bounded queue
(``FLAGS_prefetch_depth``, default 2 = classic double buffer) provides
backpressure so a slow consumer cannot pin the whole epoch in device
memory.

Failure containment: if the transfer thread dies (fault site
``io.prefetch``, device OOM on put, a poisoned sample), the consumer warns
ONCE and degrades to synchronous staging on its own thread — the batch the
thread was holding is recovered, nothing is dropped, training continues.

Telemetry flows into ``paddle.jit.cache_stats()`` under this instance's
name: ``host_blocked_ms`` (time the consumer waited for a staged batch —
the residual host-boundness after overlap) and ``avg_queue_depth`` (0
means the host pipeline is the bottleneck, ``depth`` means the device is).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
import warnings

import numpy as np

from ..core.tensor import Tensor
from ..observability import metrics as _obs_metrics
from ..observability import trace as _obs_trace

__all__ = ["DevicePrefetcher", "np_pad_to_bucket"]

# per-INSTANCE overlap telemetry (ISSUE 10 satellite): the legacy
# jit.cache_stats() row is keyed by the caller-chosen stats name, so two
# concurrent loaders built with the same name merge their numbers there.
# These registry series carry an instance label unique per prefetcher
# object, so they never merge; host_blocked is a histogram (p50/p99 of
# the per-get block, not just a total).
_M_HOST_BLOCKED = _obs_metrics.histogram(
    "io_host_blocked_ms",
    "ms the consumer blocked per staged-batch get (residual "
    "host-boundness after overlap)", buckets=_obs_metrics.DEFAULT_MS_BUCKETS)
_G_QUEUE_DEPTH = _obs_metrics.gauge(
    "io_queue_depth",
    "staged-batch queue depth at the last consumer get (0 = host-bound, "
    "prefetch_depth = device-bound)")

# worker -> consumer token kinds
_ITEM = "item"
_DONE = "done"
_ERR_SOURCE = "err_source"   # the source iterator itself raised
_ERR_STAGE = "err_stage"     # staging/transfer failed; raw batch recovered


def _array_leaves(tree, out=None):
    """Tensor/ndarray leaves of a batch tree in call order."""
    if out is None:
        out = []
    if isinstance(tree, (Tensor, np.ndarray)):
        out.append(tree)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            _array_leaves(v, out)
    elif isinstance(tree, dict):
        for v in tree.values():
            _array_leaves(v, out)
    return out


def np_pad_to_bucket(arr, spec, lengths=None):
    """Host-side (numpy) mirror of ``jit.cache.pad_array_to_bucket``:
    zero-pad ``arr`` up to its bucket under ``spec`` on the CALLING thread
    (no device work). Shared by the transfer thread below and the serving
    engine's request-ingest staging (``inference.serving``), so prompt
    padding and batch padding land on identical bucket shapes. Returns
    ``(array, was_padded)``."""
    from ..jit import cache as jit_cache

    if lengths is None:
        lengths = jit_cache.infer_call_lengths([arr], spec)
    target = jit_cache.bucketed_call_shape(arr.shape, spec, lengths)
    if tuple(target) == tuple(arr.shape):
        return arr, False
    widths = [(0, t - s) for s, t in zip(arr.shape, target)]
    return np.pad(arr, widths), True


_np_pad_to_bucket = np_pad_to_bucket  # backward-compatible alias


class DevicePrefetcher:
    """Wrap any batch iterable (``DataLoader``, a list of batches, a
    generator) so host batch production + H2D transfer overlap device
    compute. Iterating yields the same batches, staged: array leaves become
    device Tensors, padded to the active shape buckets.

    Arguments:
        source: the batch iterable. Re-iterable sources (DataLoader) give a
            fresh transfer thread per epoch.
        depth: staged-batch queue bound; default ``FLAGS_prefetch_depth``.
        shape_buckets: pad-up boundaries applied while staging (any form
            ``jit.BucketSpec.normalize`` accepts). ``None`` falls back to
            the process-global ``jit.set_shape_buckets`` spec at stage
            time, so the prefetcher and the jit layer can never disagree.
        bucket_args: like ``FusedTrainStep``'s — positional indices / dict
            keys of the batch fields to pad. Default is the same
            dominant-length rule the fused step uses, so pre-padded shapes
            are exactly the shapes the step would have padded to itself.
        name: the ``jit.cache_stats()`` row this instance reports under.
            Long-lived consumers that build prefetchers repeatedly
            (``FusedTrainStep.drive``, ``hapi.Model.fit``) pass a stable
            name so telemetry accumulates in ONE row instead of leaking a
            new auto-named row per call.
    """

    # itertools.count: atomic next() under CPython, so concurrently built
    # instances never share an auto-generated stats name
    _instance_ids = itertools.count(1)

    def __init__(self, source, depth=None, shape_buckets=None,
                 bucket_args=None, name=None):
        from ..core.flags import flag_value
        from ..jit.cache import BucketSpec

        self.source = source
        if depth is None:
            depth = int(flag_value("prefetch_depth", 2))
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = int(depth)
        self._spec = BucketSpec.normalize(shape_buckets)
        self._bucket_args = (None if bucket_args is None
                             else frozenset(bucket_args))
        uid = next(DevicePrefetcher._instance_ids)
        self._stats_name = name or f"device_prefetcher#{uid}"
        # registry label: unique PER OBJECT even when a stable name= is
        # passed, so two concurrent loaders sharing a legacy stats row
        # keep distinct io_host_blocked_ms / io_queue_depth series
        self._metrics_label = (self._stats_name if name is None
                               else f"{name}#{uid}")
        self._fell_back = False
        self._stats = {"batches": 0, "prefetched": 0, "sync_fallback": 0,
                       "host_blocked_ms": 0.0, "queue_depth_sum": 0,
                       "bucket_pads": 0}
        # live iterations' (stop event, thread, queue) triples — what
        # close() tears down when a consumer abandons iteration mid-epoch
        self._active: list = []

    def __len__(self):
        return len(self.source)

    def stats(self):
        """Instance-level overlap counters (the same numbers also land in
        ``paddle.jit.cache_stats()[<instance name>]``)."""
        d = dict(self._stats)
        d["host_blocked_ms"] = round(d["host_blocked_ms"], 3)
        n = d.pop("queue_depth_sum")
        d["avg_queue_depth"] = (round(n / d["prefetched"], 3)
                                if d["prefetched"] else None)
        d["fallback"] = self._fell_back
        return d

    # -- lifecycle -------------------------------------------------------
    def close(self):
        """Tear down any live staging thread: signal stop, drain the
        bounded queue (unblocking a transfer thread parked on ``put``),
        and join. A consumer that breaks out of iteration mid-epoch — or
        an error path like hapi ``fit``'s — calls this so the daemon
        thread never outlives the loop. Idempotent, and the prefetcher
        itself stays re-iterable (a later ``iter()`` starts a fresh
        thread over a fresh pass of the source)."""
        for stop, _t, _q in list(self._active):
            stop.set()
        for stop, t, q in list(self._active):
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=2.0)
            # a consumer that resumes its abandoned generator afterwards
            # must terminate, not block on an empty queue forever
            try:
                q.put_nowait((_DONE, None, None))
            except queue.Full:
                pass
        self._active = []
        # bound registry growth: the per-OBJECT instance series must not
        # outlive the object's working life (drive() builds a fresh
        # prefetcher per call — leaking one dead histogram + stale gauge
        # per drive would violate the label-cardinality rule). The
        # accumulated totals remain in this object's stats() and in the
        # legacy jit.cache_stats() row; a post-close re-iteration simply
        # re-creates the series.
        _M_HOST_BLOCKED.remove(instance=self._metrics_label)
        _G_QUEUE_DEPTH.remove(instance=self._metrics_label)

    def reset(self, sampler_state=None):
        """Discard every staged (read-ahead) batch and restart from the
        source: tears down live staging threads via :meth:`close`, so the
        next ``iter()`` begins a fresh pass of the source. With
        ``sampler_state`` (a ``BucketedBatchSampler.state_dict()``), the
        source's resumable sampler is first restored to that position —
        this is the divergence-rollback hook: after
        ``CheckpointManager.auto_resume`` rewinds the sampler cursor,
        ``reset`` guarantees no batch staged past the rollback point is
        ever consumed (staged batches were never ``advance()``-d, so the
        cursor and the restarted stream agree exactly)."""
        self.close()
        if sampler_state is not None:
            from . import resolve_resumable

            r = resolve_resumable(self.source)
            if r is None:
                raise TypeError(
                    f"reset(sampler_state=...) needs a resumable source; "
                    f"{type(self.source).__name__} does not expose (or "
                    "wrap something exposing) state_dict/set_state_dict/"
                    "advance")
            r.set_state_dict(sampler_state)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- staging ---------------------------------------------------------
    def _active_spec(self):
        from ..jit import cache as jit_cache

        return (self._spec if self._spec is not None
                else jit_cache.get_shape_buckets())

    def _stage(self, batch):
        """(staged batch, n_padded): pad array leaves up to their bucket
        and start the device transfer. numpy leaves pad on the host
        (np.pad — cheap, on this thread); Tensor leaves pad on device
        (dispatch is async, still off the consumer's critical path)."""
        import jax

        from ..jit import cache as jit_cache

        spec = self._active_spec()
        sel = self._bucket_args
        lengths = None
        if spec is not None and sel is None:
            arrays = [a._data if isinstance(a, Tensor) else a
                      for a in _array_leaves(batch)]
            lengths = jit_cache.infer_call_lengths(arrays, spec)
        n_pads = 0

        def stage_leaf(leaf, pad):
            nonlocal n_pads
            if isinstance(leaf, Tensor):
                arr = leaf._data
                if pad:
                    arr, p = jit_cache.pad_array_to_bucket(arr, spec, lengths)
                    n_pads += int(p)
                t = Tensor._wrap(jax.device_put(arr))
                t.stop_gradient = leaf.stop_gradient
                return t
            if isinstance(leaf, np.ndarray):
                arr = leaf
                if pad:
                    arr, p = _np_pad_to_bucket(arr, spec, lengths)
                    n_pads += int(p)
                return Tensor._wrap(jax.device_put(arr))
            return leaf

        def walk(node, field_id):
            # field selection is by top-level position/key (the step's call
            # convention: batch fields become the call's arguments)
            pad = spec is not None and (sel is None or field_id in sel)
            if isinstance(node, (Tensor, np.ndarray)):
                return stage_leaf(node, pad)
            if isinstance(node, (list, tuple)):
                if field_id is None:
                    staged = [walk(v, i) for i, v in enumerate(node)]
                else:
                    staged = [walk(v, field_id) for v in node]
                return type(node)(staged) if isinstance(node, tuple) \
                    else staged
            if isinstance(node, dict):
                if field_id is None:
                    return {k: walk(v, k) for k, v in node.items()}
                return {k: walk(v, field_id) for k, v in node.items()}
            return node

        return walk(batch, None), n_pads

    def _deliver(self, staged, n_pads, prefetched):
        from ..jit import cache as jit_cache

        if n_pads:
            jit_cache.record_bucket_pads(self._stats_name, n_pads)
            self._stats["bucket_pads"] += n_pads
        self._stats["batches"] += 1
        self._stats["prefetched" if prefetched else "sync_fallback"] += 1
        return staged

    # -- iteration -------------------------------------------------------
    def __iter__(self):
        from ..jit import cache as jit_cache
        from ..utils import fault_injection

        src = iter(self.source)
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def put(token):
            while not stop.is_set():
                try:
                    q.put(token, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            while not stop.is_set():
                try:
                    batch = next(src)
                except StopIteration:
                    put((_DONE, None, None))
                    return
                except BaseException as e:  # the LOADER failed, not us
                    put((_ERR_SOURCE, e, None))
                    return
                try:
                    fault_injection.fire("io.prefetch")
                    # staging runs on the transfer thread — an allowed
                    # span site (the host thread here exists to block)
                    with _obs_trace.span("io.prefetch.stage", cat="io",
                                         args={"instance":
                                               self._metrics_label}):
                        staged, n_pads = self._stage(batch)
                except BaseException as e:
                    # transfer thread dies; hand the un-staged batch back so
                    # the synchronous fallback loses nothing
                    put((_ERR_STAGE, e, batch))
                    return
                if not put((_ITEM, staged, n_pads)):
                    return

        t = threading.Thread(target=worker, daemon=True,
                             name=f"{self._stats_name}-transfer")
        t.start()
        entry = (stop, t, q)
        self._active.append(entry)
        pending = None
        try:
            while True:
                t0 = time.perf_counter()
                kind, payload, extra = q.get()
                blocked_ms = (time.perf_counter() - t0) * 1000.0
                if kind == _ITEM:
                    depth = q.qsize()
                    self._stats["host_blocked_ms"] += blocked_ms
                    self._stats["queue_depth_sum"] += depth
                    jit_cache.record_host_blocked(self._stats_name,
                                                  blocked_ms)
                    jit_cache.record_queue_depth(self._stats_name, depth)
                    _M_HOST_BLOCKED.observe(blocked_ms,
                                            instance=self._metrics_label)
                    _G_QUEUE_DEPTH.set(depth, instance=self._metrics_label)
                    yield self._deliver(payload, extra, prefetched=True)
                    continue
                if kind == _DONE:
                    return
                if kind == _ERR_SOURCE:
                    raise payload  # loader failure: same as synchronous
                # _ERR_STAGE: degrade to the synchronous path, once, loudly
                self._fell_back = True
                pending = extra
                warnings.warn(
                    f"DevicePrefetcher transfer thread died ({payload!r}); "
                    "falling back to synchronous host->device transfers "
                    "for the rest of this iteration",
                    RuntimeWarning, stacklevel=2)
                break
        finally:
            # early break / GeneratorExit / normal end all land here: stop
            # the transfer thread, drain whatever it staged (unconsumed
            # batches are DISCARDED — on a checkpoint resume they are
            # re-staged from the restored sampler cursor, never consumed
            # twice), and join so no thread outlives the iteration
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=2.0)
            try:
                self._active.remove(entry)
            except ValueError:
                pass
        # synchronous fallback: finish the epoch on the consumer thread
        # (no injection probe here — this IS the degraded path)
        if pending is not None:
            staged, n_pads = self._stage(pending)
            yield self._deliver(staged, n_pads, prefetched=False)
        for batch in src:
            staged, n_pads = self._stage(batch)
            yield self._deliver(staged, n_pads, prefetched=False)

    def __call__(self):
        return iter(self)
