"""Shape-bucketed batching: the input-pipeline half of the anti-recompile
subsystem.

The reference framework absorbs variable-length samples with LoD tensors fed
through DataFeed (paddle/fluid/framework/data_feed.cc); this XLA-native
design pads instead, and unpadded variable-length streams trigger one XLA
compile per distinct shape. ``BucketedBatchSampler`` groups samples by length
into a small set of buckets and ``PadToBucket`` pads every batch up to its
bucket boundary (emitting a validity mask), so a whole epoch of varying
lengths flows through O(buckets) compiled executables — the GSPMD/PaLM-style
static-shape training pipeline. The jit-side half
(``paddle.jit.set_shape_buckets`` / ``to_static(shape_buckets=...)``) covers
callers that cannot change their data pipeline; this module is the
no-wasted-flops form (batches of similar length pad less).

Both classes compose with the existing ``DataLoader`` machinery unchanged:
the sampler is a drop-in ``batch_sampler=`` (thread and process workers see
only index lists) and the collate is a picklable ``collate_fn=`` (spawn
workers ship it once; shm transport sees plain numpy arrays when
``as_tensor=False``).
"""

from __future__ import annotations

import bisect

import numpy as np

from ..core.tensor import Tensor
from . import BatchSampler

__all__ = ["BucketedBatchSampler", "PadToBucket"]


def _sample_length(sample):
    """Default length of one sample: leading dim of its FIRST array field
    (the conventional ids-first layout). Scalars/strings have no length."""
    if isinstance(sample, Tensor):
        return int(sample.shape[0]) if sample.ndim else None
    if isinstance(sample, np.ndarray):
        return int(sample.shape[0]) if sample.ndim else None
    if isinstance(sample, (list, tuple)):
        for field in sample:
            n = _sample_length(field)
            if n is not None:
                return n
        return None
    if isinstance(sample, dict):
        for field in sample.values():
            n = _sample_length(field)
            if n is not None:
                return n
        return None
    return None


class BucketedBatchSampler(BatchSampler):
    """Group sample indices by length into pad-up buckets; every yielded
    batch draws from ONE bucket, so the padded batch shapes an epoch
    produces number at most ``len(boundaries) + 1``.

    Arguments:
        dataset: map-style dataset (indexable).
        batch_size: samples per batch.
        boundaries: strictly increasing bucket upper bounds, e.g.
            ``[64, 128, 256]`` — a sample of length L lands in the first
            bucket with boundary >= L. Longer samples go to an overflow
            bucket (batched together but unbucketed in shape: each distinct
            overflow length still costs a compile, which
            ``paddle.jit.cache_stats()`` makes visible).
        lengths: optional per-sample lengths (any sequence). When omitted
            the dataset is scanned once with ``length_fn`` — pass
            precomputed lengths for datasets where ``__getitem__`` is
            expensive.
        length_fn: sample -> length; defaults to the leading dim of the
            sample's first array field.
        shuffle: shuffle samples inside each bucket AND the order of the
            yielded batches each epoch.
        drop_last: drop each bucket's trailing partial batch.
        seed: base seed for shuffling (epoch ``e`` streams from
            ``seed + e``). When omitted each epoch draws a fresh random
            seed — different order every epoch, as before — but the draw
            is *recorded* (``state_dict()``'s ``epoch_seed``) so a crash
            mid-epoch still replays the exact in-flight permutation.

    Resumable stream contract (crash recovery): the sampler carries an
    (epoch, cursor, seed) triple. The *consumer* reports consumption with
    ``advance(n)`` — one call per trained batch — so read-ahead layers
    (DataLoader workers, DevicePrefetcher staging) never inflate the
    cursor with batches that were produced but not yet trained.
    ``state_dict()/set_state_dict()`` round-trip the triple (persisted by
    ``CheckpointManager.save(sampler=...)``), and the next ``__iter__``
    skips the first ``cursor`` batches of the epoch — a restart replays
    the exact remaining batch sequence. ``set_epoch(e)`` resets the cursor
    when ``e`` differs from the current epoch (so a resume that re-enters
    the same epoch keeps its place, and the next epoch starts clean).
    """

    def __init__(self, dataset=None, batch_size=1, boundaries=None,
                 lengths=None, length_fn=None, shuffle=False, drop_last=False,
                 seed=None):
        if boundaries is None:
            raise ValueError("BucketedBatchSampler requires bucket "
                             "boundaries, e.g. boundaries=[64, 128, 256]")
        self.boundaries = tuple(sorted(int(b) for b in boundaries))
        if len(set(self.boundaries)) != len(self.boundaries):
            raise ValueError(f"duplicate boundary in {boundaries}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self._epoch = 0
        self._cursor = 0  # batches CONSUMED this epoch (advance())
        # the seed actually governing the CURRENT epoch's shuffle. Seeded:
        # seed + epoch (old behavior). Unseeded: a fresh draw per epoch
        # (old behavior) that is RECORDED here and in state_dict(), so a
        # crash mid-epoch can still replay the exact in-flight permutation
        self._epoch_seed = self._draw_epoch_seed()
        self._seed_restored = False  # pins a set_state_dict-restored seed
        # against the unseeded fresh-pass redraw (a resume at cursor 0
        # must still replay the RECORDED permutation)
        if lengths is None:
            fn = length_fn or _sample_length
            lengths = []
            for i in range(len(dataset)):
                n = fn(dataset[i])
                if n is None:
                    raise ValueError(
                        f"could not infer a length for sample {i}; pass "
                        "lengths= or length_fn=")
                lengths.append(n)
        self.lengths = [int(x) for x in lengths]
        # bucket id per sample; len(boundaries) = overflow
        self._bucket_of = [bisect.bisect_left(self.boundaries, n)
                           for n in self.lengths]

    def _draw_epoch_seed(self):
        if self.seed is not None:
            return int(self.seed) + self._epoch
        return int(np.random.randint(0, 2**31 - 1))

    def set_epoch(self, epoch):
        epoch = int(epoch)
        if epoch != self._epoch:
            # a NEW epoch starts from its first batch with a fresh stream;
            # a resume re-entering the restored epoch keeps its place
            self._cursor = 0
            self._epoch = epoch
            self._epoch_seed = self._draw_epoch_seed()
            self._seed_restored = False

    # -- resumable stream (crash recovery) -------------------------------
    def advance(self, n=1):
        """Report that ``n`` more batches of the stream were *consumed*
        (trained on, or — on the divergence-rollback path — deliberately
        skipped). Called by the training driver, not the loader, so
        prefetch read-ahead never skews the resume cursor.

        Rolling past the end of an epoch carries the remainder into the
        next epoch deterministically: the epoch increments, the cursor
        keeps the overshoot, and the epoch seed is re-drawn exactly as a
        real epoch transition would draw it (``seed + epoch`` when
        seeded) — so a rollback skip that lands near an epoch edge
        resumes the same batch sequence a step-by-step consumer would
        have seen."""
        self._cursor += int(n)
        n_batches = len(self)
        while n_batches and self._cursor >= n_batches:
            self._cursor -= n_batches
            self._epoch += 1
            self._epoch_seed = self._draw_epoch_seed()
            # the drawn seed is BINDING for the new epoch's first pass: a
            # checkpoint written at this boundary records it, so the live
            # process's next __iter__ must use it too (an unseeded
            # redraw there would make interrupted and uninterrupted runs
            # train different permutations)
            self._seed_restored = True

    def state_dict(self):
        """Resume point of the batch stream: ``(epoch, cursor, seed)``
        plus a stream fingerprint (sample count / batch size / boundaries)
        so a restore into a differently-configured pipeline fails loudly
        instead of silently replaying the wrong batches."""
        return {"epoch": self._epoch, "cursor": self._cursor,
                "epoch_seed": self._epoch_seed,
                "shuffle": bool(self.shuffle),
                "num_samples": len(self.lengths),
                "batch_size": int(self.batch_size),
                "boundaries": list(self.boundaries)}

    def set_state_dict(self, sd):
        fingerprint = {"num_samples": len(self.lengths),
                       "batch_size": int(self.batch_size),
                       "boundaries": list(self.boundaries),
                       "shuffle": bool(self.shuffle)}
        for key, have in fingerprint.items():
            if key not in sd:
                continue
            got = (list(sd[key]) if key == "boundaries"
                   else type(have)(sd[key]))
            if got != have:
                raise ValueError(
                    f"sampler state mismatch on {key!r}: checkpoint has "
                    f"{got!r}, this sampler has {have!r} — resuming "
                    "would replay a different batch sequence")
        self._epoch = int(sd["epoch"])
        self._cursor = int(sd["cursor"])
        if sd.get("epoch_seed") is not None:
            self._epoch_seed = int(sd["epoch_seed"])
            self._seed_restored = True

    load_state_dict = set_state_dict

    def bucket_histogram(self):
        """{boundary_or_'overflow': sample_count} — pipeline telemetry
        (how well the boundaries fit the data)."""
        hist = {}
        for b in self._bucket_of:
            key = (self.boundaries[b] if b < len(self.boundaries)
                   else "overflow")
            hist[key] = hist.get(key, 0) + 1
        return hist

    def _epoch_batches(self):
        """The full batch sequence of the current epoch — a pure function
        of the recorded epoch seed, so a restarted process rebuilds the
        exact same sequence before applying the resume cursor."""
        buckets: dict[int, list[int]] = {}
        order = range(len(self.lengths))
        rng = None
        if self.shuffle:
            rng = np.random.RandomState(self._epoch_seed)
            order = rng.permutation(len(self.lengths))
        for i in order:
            buckets.setdefault(self._bucket_of[i], []).append(int(i))
        batches = []
        for b in sorted(buckets):
            idxs = buckets[b]
            for lo in range(0, len(idxs), self.batch_size):
                batch = idxs[lo:lo + self.batch_size]
                if len(batch) < self.batch_size and self.drop_last:
                    continue
                batches.append(batch)
        if self.shuffle:
            batches = [batches[i] for i in rng.permutation(len(batches))]
        return batches

    def __iter__(self):
        # the cursor (batches already consumed this epoch, per advance())
        # is 0 unless a checkpoint resume restored a mid-epoch position —
        # consumers that never call advance() see full epochs, unchanged.
        # A fully-consumed epoch rolls over automatically, so resume-armed
        # loops that never call set_epoch still make progress (and a
        # checkpoint taken exactly at an epoch boundary resumes into the
        # NEXT epoch instead of yielding an empty pass).
        batches = self._epoch_batches()
        if batches and self._cursor >= len(batches):
            # carry the overshoot, don't truncate it: a restored cursor
            # past the epoch end (e.g. a rollback skip persisted at an
            # epoch edge) must land mid-next-epoch, not at its start
            while self._cursor >= len(batches):
                self._cursor -= len(batches)
                self._epoch += 1
                self._epoch_seed = self._draw_epoch_seed()
                self._seed_restored = False
            batches = self._epoch_batches()
        elif (self._cursor == 0 and self.seed is None
              and not self._seed_restored):
            # unseeded fresh pass: a new random order every epoch (the
            # pre-resumability behavior), recorded so a crash mid-pass
            # still replays this exact permutation. A seed just restored
            # by set_state_dict is pinned — a resume landing exactly on an
            # epoch boundary must replay the RECORDED permutation
            self._epoch_seed = self._draw_epoch_seed()
            batches = self._epoch_batches()
        return iter(batches[self._cursor:])

    def __len__(self):
        counts: dict[int, int] = {}
        for b in self._bucket_of:
            counts[b] = counts.get(b, 0) + 1
        if self.drop_last:
            return sum(c // self.batch_size for c in counts.values())
        return sum((c + self.batch_size - 1) // self.batch_size
                   for c in counts.values())


class PadToBucket:
    """Collate: stack samples, zero-padding each variable-length array field
    up to the batch's bucket boundary, and append a validity mask.

    Field selection: an array field is padded when its leading dim equals
    the sample's length (``length_fn``, default: leading dim of the first
    array field). Pass ``pad_fields`` (tuple indices or dict keys) to make
    the selection explicit for layouts where fixed-size fields could
    coincide with the length.

    The mask (1 = real position, 0 = padding, shape ``[B, bucket]``) is
    appended as the last tuple field / under ``mask_key`` for dict samples.
    It composes with the jit layer: downstream masked losses make the
    zero-padding mathematically inert, which is exactly the contract
    ``paddle.jit`` bucket padding assumes.

    ``as_tensor=False`` keeps the output numpy — required under process
    workers (the parent cannot unpickle device arrays cheaply, and the shm
    transport moves numpy only).
    """

    def __init__(self, boundaries, pad_value=0, with_mask=True,
                 mask_dtype="float32", mask_key="mask", length_fn=None,
                 pad_fields=None, as_tensor=True):
        self.boundaries = tuple(sorted(int(b) for b in boundaries))
        self.pad_value = pad_value
        self.with_mask = with_mask
        self.mask_dtype = mask_dtype
        self.mask_key = mask_key
        self.length_fn = length_fn or _sample_length
        self.pad_fields = pad_fields
        self.as_tensor = as_tensor

    def _bucket(self, max_len):
        i = bisect.bisect_left(self.boundaries, max_len)
        return self.boundaries[i] if i < len(self.boundaries) else max_len

    def _pad_stack(self, arrays, target):
        out = np.full((len(arrays), target) + tuple(arrays[0].shape[1:]),
                      self.pad_value, dtype=arrays[0].dtype)
        for j, a in enumerate(arrays):
            out[j, :a.shape[0]] = a
        return out

    def _finish(self, arr):
        return Tensor(arr) if self.as_tensor else arr

    def __call__(self, samples):
        samples = [self._to_numpy_tree(s) for s in samples]
        lengths = [self.length_fn(s) for s in samples]
        if any(n is None for n in lengths):
            raise ValueError("PadToBucket could not infer sample lengths; "
                             "pass length_fn=")
        target = self._bucket(max(lengths))
        mask = None
        if self.with_mask:
            mask = np.zeros((len(samples), target), dtype=self.mask_dtype)
            for j, n in enumerate(lengths):
                mask[j, :min(n, target)] = 1

        first = samples[0]
        if isinstance(first, dict):
            out = {k: self._collate_field(
                       [s[k] for s in samples], lengths,
                       target, pad=self._should_pad(k, first[k], lengths))
                   for k in first}
            if mask is not None:
                out[self.mask_key] = self._finish(mask)
            return out
        if isinstance(first, (list, tuple)):
            fields = list(zip(*samples))
            out = [self._collate_field(
                       list(f), lengths, target,
                       pad=self._should_pad(i, first[i], lengths))
                   for i, f in enumerate(fields)]
            if mask is not None:
                out.append(self._finish(mask))
            return out
        out = self._collate_field(samples, lengths, target, pad=True)
        if mask is not None:
            return [out, self._finish(mask)]
        return out

    # -- helpers --------------------------------------------------------
    def _to_numpy_tree(self, s):
        if isinstance(s, Tensor):
            return np.asarray(s._data)
        if isinstance(s, (list, tuple)):
            return type(s)(self._to_numpy_tree(v) for v in s)
        if isinstance(s, dict):
            return {k: self._to_numpy_tree(v) for k, v in s.items()}
        return s

    def _should_pad(self, field_id, field_value, lengths):
        if self.pad_fields is not None:
            return field_id in self.pad_fields
        if not isinstance(field_value, np.ndarray) or field_value.ndim == 0:
            return False
        # auto: a field is length-like when its leading dim tracks the
        # sample length (checked on the first sample)
        return int(field_value.shape[0]) == lengths[0]

    def _collate_field(self, arrays, lengths, target, pad):
        if isinstance(arrays[0], np.ndarray):
            if pad and arrays[0].ndim >= 1:
                return self._finish(self._pad_stack(arrays, target))
            return self._finish(np.stack(arrays))
        if isinstance(arrays[0], (int, float, np.number)):
            return self._finish(np.asarray(arrays))
        if isinstance(arrays[0], str):
            return list(arrays)
        return list(arrays)
