"""Shape-bucketed batching: the input-pipeline half of the anti-recompile
subsystem.

The reference framework absorbs variable-length samples with LoD tensors fed
through DataFeed (paddle/fluid/framework/data_feed.cc); this XLA-native
design pads instead, and unpadded variable-length streams trigger one XLA
compile per distinct shape. ``BucketedBatchSampler`` groups samples by length
into a small set of buckets and ``PadToBucket`` pads every batch up to its
bucket boundary (emitting a validity mask), so a whole epoch of varying
lengths flows through O(buckets) compiled executables — the GSPMD/PaLM-style
static-shape training pipeline. The jit-side half
(``paddle.jit.set_shape_buckets`` / ``to_static(shape_buckets=...)``) covers
callers that cannot change their data pipeline; this module is the
no-wasted-flops form (batches of similar length pad less).

Both classes compose with the existing ``DataLoader`` machinery unchanged:
the sampler is a drop-in ``batch_sampler=`` (thread and process workers see
only index lists) and the collate is a picklable ``collate_fn=`` (spawn
workers ship it once; shm transport sees plain numpy arrays when
``as_tensor=False``).
"""

from __future__ import annotations

import bisect

import numpy as np

from ..core.tensor import Tensor
from . import BatchSampler

__all__ = ["BucketedBatchSampler", "PadToBucket"]


def _sample_length(sample):
    """Default length of one sample: leading dim of its FIRST array field
    (the conventional ids-first layout). Scalars/strings have no length."""
    if isinstance(sample, Tensor):
        return int(sample.shape[0]) if sample.ndim else None
    if isinstance(sample, np.ndarray):
        return int(sample.shape[0]) if sample.ndim else None
    if isinstance(sample, (list, tuple)):
        for field in sample:
            n = _sample_length(field)
            if n is not None:
                return n
        return None
    if isinstance(sample, dict):
        for field in sample.values():
            n = _sample_length(field)
            if n is not None:
                return n
        return None
    return None


class BucketedBatchSampler(BatchSampler):
    """Group sample indices by length into pad-up buckets; every yielded
    batch draws from ONE bucket, so the padded batch shapes an epoch
    produces number at most ``len(boundaries) + 1``.

    Arguments:
        dataset: map-style dataset (indexable).
        batch_size: samples per batch.
        boundaries: strictly increasing bucket upper bounds, e.g.
            ``[64, 128, 256]`` — a sample of length L lands in the first
            bucket with boundary >= L. Longer samples go to an overflow
            bucket (batched together but unbucketed in shape: each distinct
            overflow length still costs a compile, which
            ``paddle.jit.cache_stats()`` makes visible).
        lengths: optional per-sample lengths (any sequence). When omitted
            the dataset is scanned once with ``length_fn`` — pass
            precomputed lengths for datasets where ``__getitem__`` is
            expensive.
        length_fn: sample -> length; defaults to the leading dim of the
            sample's first array field.
        shuffle: shuffle samples inside each bucket AND the order of the
            yielded batches each epoch.
        drop_last: drop each bucket's trailing partial batch.
        seed: base seed for shuffling (epoch-invariant streams when set).
    """

    def __init__(self, dataset=None, batch_size=1, boundaries=None,
                 lengths=None, length_fn=None, shuffle=False, drop_last=False,
                 seed=None):
        if boundaries is None:
            raise ValueError("BucketedBatchSampler requires bucket "
                             "boundaries, e.g. boundaries=[64, 128, 256]")
        self.boundaries = tuple(sorted(int(b) for b in boundaries))
        if len(set(self.boundaries)) != len(self.boundaries):
            raise ValueError(f"duplicate boundary in {boundaries}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self._epoch = 0
        if lengths is None:
            fn = length_fn or _sample_length
            lengths = []
            for i in range(len(dataset)):
                n = fn(dataset[i])
                if n is None:
                    raise ValueError(
                        f"could not infer a length for sample {i}; pass "
                        "lengths= or length_fn=")
                lengths.append(n)
        self.lengths = [int(x) for x in lengths]
        # bucket id per sample; len(boundaries) = overflow
        self._bucket_of = [bisect.bisect_left(self.boundaries, n)
                           for n in self.lengths]

    def set_epoch(self, epoch):
        self._epoch = int(epoch)

    def bucket_histogram(self):
        """{boundary_or_'overflow': sample_count} — pipeline telemetry
        (how well the boundaries fit the data)."""
        hist = {}
        for b in self._bucket_of:
            key = (self.boundaries[b] if b < len(self.boundaries)
                   else "overflow")
            hist[key] = hist.get(key, 0) + 1
        return hist

    def __iter__(self):
        buckets: dict[int, list[int]] = {}
        order = range(len(self.lengths))
        rng = None
        if self.shuffle:
            rng = np.random.RandomState(
                None if self.seed is None else self.seed + self._epoch)
            order = rng.permutation(len(self.lengths))
        for i in order:
            buckets.setdefault(self._bucket_of[i], []).append(int(i))
        batches = []
        for b in sorted(buckets):
            idxs = buckets[b]
            for lo in range(0, len(idxs), self.batch_size):
                batch = idxs[lo:lo + self.batch_size]
                if len(batch) < self.batch_size and self.drop_last:
                    continue
                batches.append(batch)
        if self.shuffle:
            batches = [batches[i] for i in rng.permutation(len(batches))]
        return iter(batches)

    def __len__(self):
        counts: dict[int, int] = {}
        for b in self._bucket_of:
            counts[b] = counts.get(b, 0) + 1
        if self.drop_last:
            return sum(c // self.batch_size for c in counts.values())
        return sum((c + self.batch_size - 1) // self.batch_size
                   for c in counts.values())


class PadToBucket:
    """Collate: stack samples, zero-padding each variable-length array field
    up to the batch's bucket boundary, and append a validity mask.

    Field selection: an array field is padded when its leading dim equals
    the sample's length (``length_fn``, default: leading dim of the first
    array field). Pass ``pad_fields`` (tuple indices or dict keys) to make
    the selection explicit for layouts where fixed-size fields could
    coincide with the length.

    The mask (1 = real position, 0 = padding, shape ``[B, bucket]``) is
    appended as the last tuple field / under ``mask_key`` for dict samples.
    It composes with the jit layer: downstream masked losses make the
    zero-padding mathematically inert, which is exactly the contract
    ``paddle.jit`` bucket padding assumes.

    ``as_tensor=False`` keeps the output numpy — required under process
    workers (the parent cannot unpickle device arrays cheaply, and the shm
    transport moves numpy only).
    """

    def __init__(self, boundaries, pad_value=0, with_mask=True,
                 mask_dtype="float32", mask_key="mask", length_fn=None,
                 pad_fields=None, as_tensor=True):
        self.boundaries = tuple(sorted(int(b) for b in boundaries))
        self.pad_value = pad_value
        self.with_mask = with_mask
        self.mask_dtype = mask_dtype
        self.mask_key = mask_key
        self.length_fn = length_fn or _sample_length
        self.pad_fields = pad_fields
        self.as_tensor = as_tensor

    def _bucket(self, max_len):
        i = bisect.bisect_left(self.boundaries, max_len)
        return self.boundaries[i] if i < len(self.boundaries) else max_len

    def _pad_stack(self, arrays, target):
        out = np.full((len(arrays), target) + tuple(arrays[0].shape[1:]),
                      self.pad_value, dtype=arrays[0].dtype)
        for j, a in enumerate(arrays):
            out[j, :a.shape[0]] = a
        return out

    def _finish(self, arr):
        return Tensor(arr) if self.as_tensor else arr

    def __call__(self, samples):
        samples = [self._to_numpy_tree(s) for s in samples]
        lengths = [self.length_fn(s) for s in samples]
        if any(n is None for n in lengths):
            raise ValueError("PadToBucket could not infer sample lengths; "
                             "pass length_fn=")
        target = self._bucket(max(lengths))
        mask = None
        if self.with_mask:
            mask = np.zeros((len(samples), target), dtype=self.mask_dtype)
            for j, n in enumerate(lengths):
                mask[j, :min(n, target)] = 1

        first = samples[0]
        if isinstance(first, dict):
            out = {k: self._collate_field(
                       [s[k] for s in samples], lengths,
                       target, pad=self._should_pad(k, first[k], lengths))
                   for k in first}
            if mask is not None:
                out[self.mask_key] = self._finish(mask)
            return out
        if isinstance(first, (list, tuple)):
            fields = list(zip(*samples))
            out = [self._collate_field(
                       list(f), lengths, target,
                       pad=self._should_pad(i, first[i], lengths))
                   for i, f in enumerate(fields)]
            if mask is not None:
                out.append(self._finish(mask))
            return out
        out = self._collate_field(samples, lengths, target, pad=True)
        if mask is not None:
            return [out, self._finish(mask)]
        return out

    # -- helpers --------------------------------------------------------
    def _to_numpy_tree(self, s):
        if isinstance(s, Tensor):
            return np.asarray(s._data)
        if isinstance(s, (list, tuple)):
            return type(s)(self._to_numpy_tree(v) for v in s)
        if isinstance(s, dict):
            return {k: self._to_numpy_tree(v) for k, v in s.items()}
        return s

    def _should_pad(self, field_id, field_value, lengths):
        if self.pad_fields is not None:
            return field_id in self.pad_fields
        if not isinstance(field_value, np.ndarray) or field_value.ndim == 0:
            return False
        # auto: a field is length-like when its leading dim tracks the
        # sample length (checked on the first sample)
        return int(field_value.shape[0]) == lengths[0]

    def _collate_field(self, arrays, lengths, target, pad):
        if isinstance(arrays[0], np.ndarray):
            if pad and arrays[0].ndim >= 1:
                return self._finish(self._pad_stack(arrays, target))
            return self._finish(np.stack(arrays))
        if isinstance(arrays[0], (int, float, np.number)):
            return self._finish(np.asarray(arrays))
        if isinstance(arrays[0], str):
            return list(arrays)
        return list(arrays)
