"""ctypes bindings to the C++ data-pipeline core (csrc/prefetch.cpp).

Builds the shared library on demand with g++ (cached next to the source).
Every entry point degrades gracefully: ``available()`` is False when no
toolchain exists and callers fall back to the numpy path.

Why native: ctypes foreign calls release the GIL, so batch collation and
image normalization run concurrently with Python-side sample loading and
with the training loop — the role the reference fills with C++ DataFeed
(paddle/fluid/framework/data_feed.cc) and worker processes
(python/paddle/io/dataloader/worker.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "lib", "collate_samples", "normalize_image_batch",
           "Ring"]

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_LIB_PATH = os.path.join(_CSRC, "libpaddle_tpu_native.so")

_lib = None
_tried = False
_build_lock = threading.Lock()


def _build():
    src = os.path.join(_CSRC, "prefetch.cpp")
    if not os.path.exists(src):
        return False
    # compile to a private temp file and atomically rename into place, so a
    # sibling launcher rank never dlopens a half-written .so
    tmp = _LIB_PATH + f".tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-pthread", "-shared",
             "-o", tmp, src],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def lib_ready():
    """The already-loaded CDLL or None — never builds (hot-path probe)."""
    return _lib


def warm(background=True):
    """Ensure the library is built/loaded. With background=True the g++ run
    happens on a daemon thread so callers (DataLoader init) never block; the
    hot path keeps using the numpy fallback until the library is ready."""
    if _lib is not None or _tried:
        return
    if background:
        threading.Thread(target=lib, daemon=True).start()
    else:
        lib()


def lib():
    """The loaded CDLL or None (builds on first call if needed)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _build_lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src = os.path.join(_CSRC, "prefetch.cpp")
        stale = (os.path.exists(_LIB_PATH) and os.path.exists(src)
                 and os.path.getmtime(_LIB_PATH) < os.path.getmtime(src))
        if not os.path.exists(_LIB_PATH) or stale:
            if not _build() and not os.path.exists(_LIB_PATH):
                return None
            # rebuild failure with a stale-but-loadable .so on disk: use it
        try:
            L = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        L.pt_collate.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int]
        L.pt_img_normalize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int]
        L.pt_ring_new.restype = ctypes.c_void_p
        L.pt_ring_new.argtypes = [ctypes.c_int64]
        L.pt_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_int64]
        L.pt_ring_pop.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.c_int64]
        L.pt_ring_size.restype = ctypes.c_int64
        L.pt_ring_size.argtypes = [ctypes.c_void_p]
        L.pt_ring_close.argtypes = [ctypes.c_void_p]
        L.pt_ring_free.argtypes = [ctypes.c_void_p]
        _lib = L
    return _lib


def available():
    return lib() is not None


def collate_samples(samples, n_threads=4):
    """np.stack(samples) computed by the native parallel-memcpy collator.
    samples: list of same-shape/dtype contiguous ndarrays. Returns None if
    the native path can't apply (caller falls back to np.stack)."""
    L = lib()
    if L is None or not samples:
        return None
    first = samples[0]
    if not isinstance(first, np.ndarray):
        return None
    shape, dtype = first.shape, first.dtype
    if dtype == object:
        return None
    arrs = []
    for s in samples:
        if not isinstance(s, np.ndarray) or s.shape != shape \
                or s.dtype != dtype:
            return None
        arrs.append(np.ascontiguousarray(s))
    out = np.empty((len(arrs),) + shape, dtype)
    sample_bytes = first.nbytes
    # thread count scaled to the work: one thread per ~4MB of batch
    total = sample_bytes * len(arrs)
    n_threads = max(1, min(int(n_threads), total >> 22))
    ptrs = (ctypes.c_void_p * len(arrs))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs])
    L.pt_collate(ptrs, len(arrs), sample_bytes,
                 out.ctypes.data_as(ctypes.c_void_p), int(n_threads))
    return out


def normalize_image_batch(images, mean, std, n_threads=4):
    """HWC uint8 images -> NCHW float32 normalized, fused in C++.
    images: list of [H, W, C] uint8 arrays (same shape). Returns None if
    inapplicable."""
    L = lib()
    if L is None or not images:
        return None
    first = images[0]
    if not isinstance(first, np.ndarray) or first.dtype != np.uint8 \
            or first.ndim != 3:
        return None
    h, w, c = first.shape
    arrs = []
    for im in images:
        if not isinstance(im, np.ndarray) or im.shape != (h, w, c) \
                or im.dtype != np.uint8:
            return None
        arrs.append(np.ascontiguousarray(im))
    mean = np.asarray(mean, np.float32).reshape(-1)
    std = np.asarray(std, np.float32).reshape(-1)
    if mean.size != c or std.size != c:
        return None
    out = np.empty((len(arrs), c, h, w), np.float32)
    ptrs = (ctypes.c_void_p * len(arrs))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs])
    L.pt_img_normalize_batch(
        ptrs, out.ctypes.data_as(ctypes.c_void_p), len(arrs), h, w, c,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), int(n_threads))
    return out


class Ring:
    """Blocking MPMC token ring (the prefetch queue between C-side-friendly
    producers and the consumer). Tokens are uint64 ids the Python side maps
    to objects."""

    def __init__(self, capacity):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._L = L
        self._h = L.pt_ring_new(int(capacity))

    def push(self, token, timeout_ms=-1):
        return self._L.pt_ring_push(self._h, int(token), int(timeout_ms))

    def pop(self, timeout_ms=-1):
        tok = ctypes.c_uint64()
        rc = self._L.pt_ring_pop(self._h, ctypes.byref(tok), int(timeout_ms))
        return rc, tok.value

    def __len__(self):
        return self._L.pt_ring_size(self._h)

    def close(self):
        self._L.pt_ring_close(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._L.pt_ring_close(self._h)
                self._L.pt_ring_free(self._h)
                self._h = None
        except Exception:
            pass
