"""paddle.geometric — graph message passing + segment ops.

Reference: python/paddle/geometric/ (math.py segment_* :23-197,
message_passing/send_recv.py send_u_recv :36, send_ue_recv :187,
send_uv :392). TPU-native: every primitive is a jax segment reduction
(``jax.ops.segment_*``) or gather — XLA lowers both to fused
scatter/gather, the same kernels the reference's graph_send_recv CUDA ops
hand-write. Static ``num_segments`` comes from ``out_size`` when given
(required under jit; eager infers it from the data).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max",
           "send_u_recv", "send_ue_recv", "send_uv"]

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # sum/count
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _num_segments(ids, out_size):
    if out_size is not None and int(out_size) > 0:
        return int(out_size)
    data = ids._data if isinstance(ids, Tensor) else ids
    if isinstance(data, jax.core.Tracer):
        raise ValueError(
            "segment/send_recv ops need out_size under jit (the output "
            "shape must be static); pass out_size=max(dst)+1")
    return int(np.max(np.asarray(data))) + 1 if np.size(data) else 0


def _segment(reduce_op, data, ids, n):
    if reduce_op == "mean":
        s = jax.ops.segment_sum(data, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(ids.shape, data.dtype), ids,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1).reshape(
            (-1,) + (1,) * (data.ndim - 1))
    out = _REDUCERS[reduce_op](data, ids, num_segments=n)
    if reduce_op in ("max", "min"):
        # empty segments produce +-inf in jax; the reference fills 0
        return jnp.where(jnp.isfinite(out), out, 0)
    return out


@op("segment_reduce")
def _segment_op(data, ids, reduce_op="sum", n=0):
    return _segment(reduce_op, data, ids.astype(jnp.int32), n)


def _make_segment(name):
    def fn(data, segment_ids, name_=None):
        n = _num_segments(segment_ids, None)
        return _segment_op(data, segment_ids, reduce_op=name, n=n)

    fn.__name__ = f"segment_{name}"
    fn.__doc__ = f"reference geometric/math.py segment_{name}."
    return fn


segment_sum = _make_segment("sum")
segment_mean = _make_segment("mean")
segment_min = _make_segment("min")
segment_max = _make_segment("max")


@op("send_u_recv_op")
def _send_u_recv(x, src, dst, reduce_op="sum", n=0):
    msgs = jnp.take(x, src.astype(jnp.int32), axis=0)
    return _segment(reduce_op, msgs, dst.astype(jnp.int32), n)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """reference send_recv.py:36 — gather x[src], segment-reduce at dst."""
    n = _num_segments(dst_index, out_size)
    return _send_u_recv(x, src_index, dst_index, reduce_op=str(reduce_op),
                        n=n)


@op("send_ue_recv_op")
def _send_ue_recv(x, y, src, dst, message_op="add", reduce_op="sum", n=0):
    msgs = jnp.take(x, src.astype(jnp.int32), axis=0)
    if message_op == "add":
        msgs = msgs + y
    elif message_op == "sub":
        msgs = msgs - y
    elif message_op == "mul":
        msgs = msgs * y
    elif message_op == "div":
        msgs = msgs / y
    else:
        raise ValueError(f"unknown message_op {message_op!r}")
    return _segment(reduce_op, msgs, dst.astype(jnp.int32), n)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """reference send_recv.py:187 — node-edge compute then reduce."""
    n = _num_segments(dst_index, out_size)
    return _send_ue_recv(x, y, src_index, dst_index,
                         message_op=str(message_op),
                         reduce_op=str(reduce_op), n=n)


@op("send_uv_op")
def _send_uv(x, y, src, dst, message_op="add"):
    xs = jnp.take(x, src.astype(jnp.int32), axis=0)
    yd = jnp.take(y, dst.astype(jnp.int32), axis=0)
    if message_op == "add":
        return xs + yd
    if message_op == "sub":
        return xs - yd
    if message_op == "mul":
        return xs * yd
    if message_op == "div":
        return xs / yd
    raise ValueError(f"unknown message_op {message_op!r}")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """reference send_recv.py:392 — per-edge message from both endpoints."""
    return _send_uv(x, y, src_index, dst_index, message_op=str(message_op))


# ---------------------------------------------------------------------------
# graph sampling / reindex (reference python/paddle/geometric/reindex.py,
# sampling/neighbors.py — CPU kernels in the reference too; sampling is
# host-side data preparation, the compiled path consumes its outputs)
# ---------------------------------------------------------------------------

def _np(x):
    import numpy as np

    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """(reindex_src, reindex_dst, out_nodes): relabel a sampled subgraph to
    local ids — x's nodes first, new neighbor nodes in appearance order
    (reference geometric/reindex.py:20)."""
    import numpy as np

    from ..core.tensor import Tensor

    xs = _np(x).astype(np.int64)
    nb = _np(neighbors).astype(np.int64)
    cnt = _np(count).astype(np.int64)
    mapping = {}
    for v in xs:
        mapping.setdefault(int(v), len(mapping))
    for v in nb:
        mapping.setdefault(int(v), len(mapping))
    out_nodes = np.fromiter(mapping.keys(), np.int64, len(mapping))
    src = np.fromiter((mapping[int(v)] for v in nb), np.int64, len(nb))
    dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    return Tensor(src), Tensor(dst), Tensor(out_nodes)


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: per-edge-type neighbor/count lists share one
    node relabeling (reference geometric/reindex.py:129)."""
    import numpy as np

    from ..core.tensor import Tensor

    xs = _np(x).astype(np.int64)
    mapping = {}
    for v in xs:
        mapping.setdefault(int(v), len(mapping))
    srcs, dsts = [], []
    for nb_t, cnt_t in zip(neighbors, count):
        nb = _np(nb_t).astype(np.int64)
        cnt = _np(cnt_t).astype(np.int64)
        for v in nb:
            mapping.setdefault(int(v), len(mapping))
        srcs.append(np.fromiter((mapping[int(v)] for v in nb), np.int64,
                                len(nb)))
        dsts.append(np.repeat(np.arange(len(xs), dtype=np.int64), cnt))
    out_nodes = np.fromiter(mapping.keys(), np.int64, len(mapping))
    return (Tensor(np.concatenate(srcs)), Tensor(np.concatenate(dsts)),
            Tensor(out_nodes))


def _sample(colptr_np, row_np, nodes_np, k, weights=None, rng=None):
    import numpy as np

    outs, counts, eids = [], [], []
    for v in nodes_np:
        lo, hi = int(colptr_np[v]), int(colptr_np[v + 1])
        deg = hi - lo
        if k < 0 or deg <= k:
            pick = np.arange(lo, hi)
        elif weights is None:
            pick = lo + rng.choice(deg, size=k, replace=False)
        else:
            w = weights[lo:hi].astype(np.float64)
            p = w / w.sum() if w.sum() > 0 else None
            pick = lo + rng.choice(deg, size=k, replace=False, p=p)
        outs.append(row_np[pick])
        eids.append(pick)
        counts.append(len(pick))
    return (np.concatenate(outs) if outs else np.zeros(0, np.int64),
            np.asarray(counts, np.int64),
            np.concatenate(eids) if eids else np.zeros(0, np.int64))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform k-neighbor sampling over a CSC graph (reference
    geometric/sampling/neighbors.py:24). Returns (out_neighbors,
    out_count[, out_eids])."""
    import numpy as np

    from ..core import rng as _rng
    from ..core.tensor import Tensor

    import jax

    seed = int(jax.random.randint(_rng.next_key(), (), 0, 2**31 - 1))
    gen = np.random.default_rng(seed)
    nb, cnt, picked = _sample(_np(colptr), _np(row).astype(np.int64),
                              _np(input_nodes).astype(np.int64),
                              int(sample_size), rng=gen)
    if return_eids:
        eid_arr = _np(eids).astype(np.int64)[picked] if eids is not None \
            else picked
        return Tensor(nb), Tensor(cnt), Tensor(eid_arr)
    return Tensor(nb), Tensor(cnt)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional variant (reference sampling/neighbors.py:159)."""
    import numpy as np

    from ..core import rng as _rng
    from ..core.tensor import Tensor

    import jax

    seed = int(jax.random.randint(_rng.next_key(), (), 0, 2**31 - 1))
    gen = np.random.default_rng(seed)
    nb, cnt, picked = _sample(_np(colptr), _np(row).astype(np.int64),
                              _np(input_nodes).astype(np.int64),
                              int(sample_size), weights=_np(edge_weight),
                              rng=gen)
    if return_eids:
        eid_arr = _np(eids).astype(np.int64)[picked] if eids is not None \
            else picked
        return Tensor(nb), Tensor(cnt), Tensor(eid_arr)
    return Tensor(nb), Tensor(cnt)


__all__ += ["reindex_graph", "reindex_heter_graph", "sample_neighbors",
            "weighted_sample_neighbors"]
