"""Row-sparse gradient representation for embedding lookups.

The problem (PERF.md round 6, BENCH_r05): DeepFM's 1M-row embedding tables
train at 0.4% MFU because every step materializes a dense ``[vocab, dim]``
gradient (the transpose of the gather is a vocab-sized scatter-add) and the
optimizer then streams the full table plus BOTH Adam moments through HBM to
update the ~0.04% of rows a batch actually touches. The reference's answer
is ``Adam(lazy_mode=True)`` over SelectedRows gradients
(``paddle/phi/kernels/selected_rows/adam_kernel.h``); this module is the
JAX-native equivalent.

Mechanism: JAX's ``custom_vjp`` cannot return a sparse cotangent for a dense
input (cotangent structure must match the primal), so the row-sparse backward
is built the other way around — the lookup is *captured*:

1. the table enters the loss through ``jax.lax.stop_gradient`` (no dense
   cotangent is ever built), and
2. the gathered rows get a zeros ``[n_ids, dim]`` **delta** added — a real
   differentiation input, so ``grad`` w.r.t. the delta is exactly the
   per-occurrence row gradient, at batchxfields size instead of vocab size.

Duplicate ids are then segment-summed into unique slots
(:func:`segment_rows`) with a **static** size bound ``n_ids = batch*fields``
— shapes stay bucket-stable for the PR-1 jit cache; the dynamic "how many
unique" lives in a ``valid`` mask, never in a shape. The capture is
activated by :class:`FusedTrainStep` (see ``incubate/fused_train_step.py``)
around its traced loss; ``F.embedding`` / ``F.embedding_bag`` consult
:func:`captured_lookup` / :func:`captured_pooled_lookup` and take the
delta route when their table is registered.

Eager mode has no trace to capture, so :func:`note_eager_lookup` records
the looked-up ids at forward time (``SparseEmbedding.forward``) and the
eager ``Adam(lazy_mode=True)`` path consumes them to gather the touched
rows of the (dense) autograd gradient.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

__all__ = [
    "SparseCapture", "capture", "active_capture", "captured_lookup",
    "captured_pooled_lookup", "segment_rows", "note_eager_lookup",
    "consume_eager_lookups", "peek_eager_lookups",
]

_TLS = threading.local()


class SparseCapture:
    """One trace's capture state.

    ``registry`` maps ``id(weight array)`` (the traced table array as bound
    by ``functional_call``) to the parameter's structured name. Two modes:

    - ``discover``: an abstract pass (``jax.eval_shape``) that only records
      each lookup's flattened id count per table, so the caller can build
      the zero deltas *before* differentiating;
    - ``apply``: the real pass — each lookup consumes its delta (in call
      order, which is deterministic because tracing is) and records its
      flattened ids for the backward's dedup.
    """

    def __init__(self, registry, mode, deltas=None):
        self.registry = dict(registry)
        self.mode = mode  # "discover" | "apply"
        self.deltas = deltas or {}  # name -> list of [n_ids, dim] arrays
        self.counts = {}  # name -> per-lookup n_ids (discover)
        self.ids = {}  # name -> per-lookup flat ids (apply)
        self._cursor = {}  # name -> next delta index (apply)

    def match(self, weight):
        return self.registry.get(id(weight))

    def on_lookup(self, name, flat_ids, rows):
        """Route one lookup's gathered rows through its delta."""
        if self.mode == "discover":
            self.counts.setdefault(name, []).append(int(flat_ids.shape[0]))
            return rows
        i = self._cursor.get(name, 0)
        self._cursor[name] = i + 1
        chunk = self.deltas[name][i]
        self.ids.setdefault(name, []).append(flat_ids)
        return rows + chunk.astype(rows.dtype)


class _Scope:
    def __init__(self, cap):
        self.cap = cap

    def __enter__(self):
        prev = getattr(_TLS, "capture", None)
        if prev is not None:
            raise RuntimeError("sparse-grad captures do not nest")
        _TLS.capture = self.cap
        return self.cap

    def __exit__(self, *exc):
        _TLS.capture = None
        return False


def capture(registry, mode, deltas=None):
    """Context manager installing a :class:`SparseCapture` for this thread."""
    return _Scope(SparseCapture(registry, mode, deltas))


def active_capture():
    return getattr(_TLS, "capture", None)


def captured_lookup(x, weight):
    """The capture hook ``F.embedding`` consults. Returns the looked-up
    ``x.shape + (dim,)`` rows when ``weight`` is a registered table inside
    an active capture, else ``None`` (caller takes the dense gather).

    The forward value is bit-identical to the dense gather — the delta is
    zeros — but the table itself is wrapped in ``stop_gradient``, so the
    backward produces ``[n_ids, dim]`` delta grads instead of a
    vocab-sized scatter-add."""
    cap = active_capture()
    if cap is None:
        return None
    name = cap.match(weight)
    if name is None:
        return None
    flat = x.reshape(-1)
    rows = jnp.take(jax.lax.stop_gradient(weight), flat, axis=0)
    rows = cap.on_lookup(name, flat, rows)
    return rows.reshape(tuple(x.shape) + (weight.shape[-1],))


def captured_pooled_lookup(x, weight, mode):
    """Capture hook for the fused lookup+pool (``F.embedding_bag``):
    gathered rows flow through the delta, then the pool reduces over the
    field axis in the same expression — the ``[B, F, dim]`` intermediate
    is never handed to another op, so XLA fuses gather+reduce into one
    loop. Returns ``[B, dim]`` or ``None`` when not captured."""
    cap = active_capture()
    if cap is None:
        return None
    name = cap.match(weight)
    if name is None:
        return None
    flat = x.reshape(-1)
    rows = jnp.take(jax.lax.stop_gradient(weight), flat, axis=0)
    rows = cap.on_lookup(name, flat, rows)
    rows = rows.reshape(tuple(x.shape) + (weight.shape[-1],))
    if mode == "mean":
        return rows.mean(axis=-2)
    return rows.sum(axis=-2)


def _dedup_plan(ids):
    """The one shared slot layout every dedup consumer depends on (the
    masked-slot aliasing in ``lazy_adam_rows`` relies on it): sort the
    ids, flag segment heads, and assign each sorted position its unique
    slot. Returns ``(order, sids, slot, valid)`` for non-empty ``ids``."""
    K = int(ids.shape[0])
    order = jnp.argsort(ids)
    sids = ids[order]
    head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sids[1:] != sids[:-1]])
    slot = jnp.cumsum(head) - 1  # [K] in [0, n_unique)
    valid = jnp.arange(K) < jnp.sum(head)
    return order, sids, slot, valid


def unique_ids(ids):
    """Static-shape dedup of a flat id vector: ``(uniq_ids [K],
    valid [K])`` with each distinct id once in the leading slots (the
    :func:`segment_rows` slot layout, via the shared :func:`_dedup_plan`).
    Pure jnp — call it inside a jitted consumer so the sort/cumsum fuse
    into its executable."""
    if int(ids.shape[0]) == 0:
        return ids, jnp.zeros((0,), jnp.bool_)
    _, sids, slot, valid = _dedup_plan(ids)
    return jnp.zeros_like(sids).at[slot].set(sids), valid


def lookup_only_tables(closed_jaxpr, tables):
    """Which of ``tables`` (name -> array, matched by IDENTITY against the
    jaxpr's consts) are consumed ONLY through ``stop_gradient`` — i.e. the
    capture's lookup route — in the traced loss?

    This is the safety gate for the row-sparse path: a table used anywhere
    else (tied output projection, a direct matmul, a dtype cast before the
    lookup that breaks identity matching) would silently lose that
    gradient contribution, so such tables must fall back to the dense
    path. The check is conservative: any non-``stop_gradient`` consumer —
    including an opaque sub-call the table is passed into — marks the
    table unsafe. Returns the set of SAFE names."""
    jaxpr = closed_jaxpr.jaxpr
    var_of = {}
    for cv, cval in zip(jaxpr.constvars, closed_jaxpr.consts):
        for name, arr in tables.items():
            if cval is arr:
                var_of[name] = cv
    safe = set()
    for name in tables:
        v = var_of.get(name)
        if v is None:
            safe.add(name)  # never consumed at all: no gradient to lose
            continue
        ok = True
        for eqn in jaxpr.eqns:
            if any(iv is v for iv in eqn.invars) \
                    and eqn.primitive.name != "stop_gradient":
                ok = False
                break
        if ok:
            safe.add(name)
    return safe


def segment_rows(ids, vals, combine="add"):
    """Deduplicate row gradients into unique slots with STATIC shapes.

    ``ids [K]`` int, ``vals [K, dim]``. Returns ``(uniq_ids [K],
    uniq_vals [K, dim], valid [K] bool)`` where the first ``n_unique``
    slots hold each distinct id once; slots beyond that are zero and
    masked out by ``valid``. K is the static bound (batch*fields), so the
    output shape never depends on the batch's id distribution — the price
    is carrying dead slots, which the consumer masks.

    ``combine="add"`` sums duplicates (per-occurrence delta grads — the
    segment-sum dedup); ``combine="set"`` keeps one representative
    (rows gathered from an already-summed dense gradient, where summing
    duplicates would multiply-count)."""
    if int(ids.shape[0]) == 0:
        return ids, vals, jnp.zeros((0,), jnp.bool_)
    order, sids, slot, valid = _dedup_plan(ids)
    svals = vals[order]
    if combine == "add":
        uniq_vals = jnp.zeros_like(svals).at[slot].add(svals)
    else:  # duplicates of one id carry identical values: set is exact
        uniq_vals = jnp.zeros_like(svals).at[slot].set(svals)
    uniq_ids = jnp.zeros_like(sids).at[slot].set(sids)
    return uniq_ids, uniq_vals, valid


# ---------------------------------------------------------------------------
# eager-mode lookup recording (the lazy path's id source outside a trace)
# ---------------------------------------------------------------------------

# The record lives ON the table's Tensor (``_lazy_lookup_rec`` attribute):
# its lifecycle is the tensor's — no global registry, no stale entries for
# collected tables, no id()-reuse aliasing one table's ids onto another.
# Consume-on-step protocol; a non-lazy optimizer never consumes, so the
# per-table list is capped: past _MAX_CHUNKS it collapses to an OVERFLOW
# marker until the next consume resets it (dense fallback — always
# correct; silently dropping chunks could LOSE touched rows instead).
_REC_ATTR = "_lazy_lookup_rec"
_OVERFLOW = "overflow"
_MAX_CHUNKS = 32


def note_eager_lookup(weight_tensor, ids):
    """Record one eager lookup's ids against the table parameter (called
    from ``SparseEmbedding.forward`` outside a trace). The eager
    ``Adam(lazy_mode=True)`` update consumes these to know which rows of
    the dense autograd gradient are live."""
    cur = getattr(weight_tensor, _REC_ATTR, None)
    if cur is _OVERFLOW:
        return
    arr = ids._data if hasattr(ids, "_data") else jnp.asarray(ids)
    if cur is None:
        cur = []
        setattr(weight_tensor, _REC_ATTR, cur)
    cur.append(arr.reshape(-1).astype(jnp.int32))
    if len(cur) > _MAX_CHUNKS:
        setattr(weight_tensor, _REC_ATTR, _OVERFLOW)


def peek_eager_lookups(weight_tensor):
    got = getattr(weight_tensor, _REC_ATTR, None)
    return None if got is _OVERFLOW else got


def consume_eager_lookups(weight_tensor):
    """Pop and concatenate the recorded flat ids for this table. Returns
    ``None`` (→ dense path) when nothing was recorded since the last
    consume, or when the record overflowed (an un-consuming optimizer or
    >32 forwards of gradient accumulation — the dense update stays
    correct either way)."""
    chunks = getattr(weight_tensor, _REC_ATTR, None)
    if chunks is not None:
        setattr(weight_tensor, _REC_ATTR, None)
    if not chunks or chunks is _OVERFLOW:
        return None
    return chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks)
