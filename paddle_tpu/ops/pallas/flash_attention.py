"""Flash attention — Pallas TPU kernels.

Replaces the reference's CUDA flash-attention binding
(paddle/phi/kernels/gpu/flash_attn_kernel.cu + third_party/flashattn) with a
TPU-native online-softmax kernel:

* forward: one pass over KV blocks per Q block, fp32 accumulators in VMEM,
  saves per-row logsumexp for the backward
* backward: recompute-style — a dQ kernel (loop over KV) and a dKV kernel
  (loop over Q), the standard FlashAttention-2 split
* causal masking bounds the KV loop per Q block (traced fori_loop bound), so
  causal attention does ~half the FLOPs — the analog of the CUDA kernel's
  block early-exit

Layout contract: [batch, seq, heads, head_dim] (paddle convention) at the API;
kernels run on [batch*heads, seq, head_dim]. The nn.functional wrapper only
routes here when head_dim % 128 == 0 and seq divides the block size — else it
falls back to the XLA path.
"""

from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _block_sizes(seq_q, seq_k):
    bq = min(512, seq_q)
    bk = min(512, seq_k)
    while seq_q % bq:
        bq //= 2
    while seq_k % bk:
        bk //= 2
    return max(bq, 8), bk


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k):
    _, bq, d = q_ref.shape
    sk = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale

    acc = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)

    num_k = sk // block_k
    if causal:
        num_k_run = jnp.minimum(
            num_k, ((qi + 1) * bq + block_k - 1) // block_k)
    else:
        num_k_run = num_k

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                       (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                            (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * corr + jax.lax.dot(p, v,
                                       preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, num_k_run, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, 0]


def _fwd(q, k, v, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref, *,
                   scale, causal, block_k):
    _, bq, d = q_ref.shape
    sk = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    o = o_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, None]
    delta = jnp.sum(do * o, axis=1, keepdims=True)

    num_k = sk // block_k
    if causal:
        num_k_run = jnp.minimum(
            num_k, ((qi + 1) * bq + block_k - 1) // block_k)
    else:
        num_k_run = num_k

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                       (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                            (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_k_run, body,
                           jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref,
                    dv_ref, *, scale, causal, block_q):
    _, bk, d = k_ref.shape
    sq = q_ref.shape[1]
    kb = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    num_q = sq // block_q
    if causal:
        # first q block that sees this kv block
        q_start = (kb * bk) // block_q
    else:
        q_start = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        o = o_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        delta = jnp.sum(do * o, axis=1, keepdims=True)
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = kb * bk + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        q_start, num_q, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, dout):
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
    )(q, k, v, dout, out, lse)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, sq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
    )(q, k, v, dout, out, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_mha(q, k, v, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_mha_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_mha_bwd(scale, causal, block_q, block_k, res, dout):
    return _bwd(scale, causal, block_q, block_k, res, dout)


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


from ...core.dispatch import op as _op


@_op("flash_attention_pallas")
def _flash_attention_arrays(q, k, v, causal=True, scale=None):
    """q/k/v: [B, S, H, D] (paddle layout). GQA: kv heads broadcast."""
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2).reshape(b * hq, sq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * hq, k.shape[1], d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * hq, v.shape[1], d)
    bq, bk = _block_sizes(sq, kt.shape[1])
    out = _flash_mha(qt, kt, vt, float(s), bool(causal), bq, bk)
    return jnp.swapaxes(out.reshape(b, hq, sq, d), 1, 2)


def flash_attention_fwd(q, k, v, causal=True, scale=None):
    """Tensor-level entry used by nn.functional (dispatch wraps autograd)."""
    return _flash_attention_arrays(q, k, v, causal=bool(causal), scale=scale)
