"""Flash attention — Pallas TPU kernels.

Replaces the reference's CUDA flash-attention binding
(paddle/phi/kernels/gpu/flash_attn_kernel.cu + third_party/flashattn) with a
TPU-native online-softmax kernel:

* forward: one pass over KV blocks per Q block, fp32 accumulators in VMEM,
  saves per-row logsumexp for the backward
* backward: recompute-style — a dQ kernel (loop over KV) and a dKV kernel
  (loop over Q), the standard FlashAttention-2 split
* causal masking bounds the KV loop per Q block (traced fori_loop bound), so
  causal attention does ~half the FLOPs — the analog of the CUDA kernel's
  block early-exit

Layout contract: [batch, seq, heads, head_dim] (paddle convention) at the API;
kernels run on [batch*heads, seq, head_dim]. The nn.functional wrapper only
routes here when head_dim % 128 == 0 and seq divides the block size — else it
falls back to the XLA path.
"""

from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


import os


def _interpret():
    """Run the kernels in Pallas interpret mode (CPU parity tests)."""
    return os.environ.get("PT_PALLAS_INTERPRET", "0") == "1"


def _pick_block(env_var, default, extent, floor=1):
    """Largest size <= min(env override, default) that divides ``extent``
    (halving search), clamped to ``floor``. Shared by all Pallas modules."""
    b = min(int(os.environ.get(env_var, default)), extent)
    while extent % b:
        b //= 2
    return max(b, floor)


def _block_sizes(seq_q, seq_k):
    return (_pick_block("PT_FA_BQ", 512, seq_q, floor=8),
            _pick_block("PT_FA_BK", 512, seq_k))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _rot_f32(x, c, s):
    """Apply rotary embedding in-register: x (n, d) f32, c/s full-width
    (n, d) cos/sin tables. rot(x) = [-x2, x1]; rope(x) = x*c + rot(x)*s.
    The inverse rotation (used on gradients) is the same with s negated."""
    d2 = x.shape[-1] // 2
    rot = jnp.concatenate([-x[:, d2:], x[:, :d2]], axis=-1)
    return x * c + rot * s


def _fwd_kernel(*refs, scale, causal, block_k, rope=False):
    if rope:
        q_ref, k_ref, v_ref, cs_ref, sn_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
    _, bq, d = q_ref.shape
    sk = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    if rope:
        qsl = pl.ds(qi * bq, bq)
        q = _rot_f32(q, cs_ref[qsl, :], sn_ref[qsl, :])
    q = q * scale

    acc = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)

    num_k = sk // block_k
    if causal:
        num_k_run = jnp.minimum(
            num_k, ((qi + 1) * bq + block_k - 1) // block_k)
    else:
        num_k_run = num_k

    def body(kb, carry):
        acc, m, l = carry
        ksl = pl.ds(kb * block_k, block_k)
        k = k_ref[0, ksl, :].astype(jnp.float32)
        v = v_ref[0, ksl, :].astype(jnp.float32)
        if rope:
            k = _rot_f32(k, cs_ref[ksl, :], sn_ref[ksl, :])
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                       (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                            (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * corr + jax.lax.dot(p, v,
                                       preferred_element_type=jnp.float32)
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, num_k_run, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, 0]


def _fwd(q, k, v, scale, causal, block_q, block_k, rope_cs=None):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
    ]
    args = [q, k, v]
    if rope_cs is not None:
        in_specs += [pl.BlockSpec((sk, d), lambda b, i: (0, 0))] * 2
        args += list(rope_cs)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_k=block_k, rope=rope_cs is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(*refs, scale, causal, block_k, rope=False):
    if rope:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, cs_ref, sn_ref,
         dq_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref = refs
    _, bq, d = q_ref.shape
    sk = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    if rope:
        qsl = pl.ds(qi * bq, bq)
        q = _rot_f32(q, cs_ref[qsl, :], sn_ref[qsl, :])
    do = do_ref[0].astype(jnp.float32)
    o = o_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, None]
    delta = jnp.sum(do * o, axis=1, keepdims=True)

    num_k = sk // block_k
    if causal:
        num_k_run = jnp.minimum(
            num_k, ((qi + 1) * bq + block_k - 1) // block_k)
    else:
        num_k_run = num_k

    def body(kb, dq):
        ksl = pl.ds(kb * block_k, block_k)
        k = k_ref[0, ksl, :].astype(jnp.float32)
        v = v_ref[0, ksl, :].astype(jnp.float32)
        if rope:
            k = _rot_f32(k, cs_ref[ksl, :], sn_ref[ksl, :])
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32,
                                                       (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                            (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_k_run, body,
                           jnp.zeros((bq, d), jnp.float32))
    if rope:
        # grads rotate back through the q rope (inverse = negated sin)
        qsl = pl.ds(qi * bq, bq)
        dq = _rot_f32(dq, cs_ref[qsl, :], -sn_ref[qsl, :])
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, rope=False):
    if rope:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, cs_ref, sn_ref,
         dk_ref, dv_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref, dv_ref = refs
    _, bk, d = k_ref.shape
    sq = q_ref.shape[1]
    kb = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    if rope:
        kvsl = pl.ds(kb * bk, bk)
        k = _rot_f32(k, cs_ref[kvsl, :], sn_ref[kvsl, :])
    v = v_ref[0].astype(jnp.float32)

    num_q = sq // block_q
    if causal:
        # first q block that sees this kv block
        q_start = (kb * bk) // block_q
    else:
        q_start = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        if rope:
            qsl = pl.ds(qi * block_q, block_q)
            q = _rot_f32(q, cs_ref[qsl, :], sn_ref[qsl, :])
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        o = o_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        delta = jnp.sum(do * o, axis=1, keepdims=True)
        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = kb * bk + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        q_start, num_q, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    if rope:
        kvsl = pl.ds(kb * bk, bk)
        dk = _rot_f32(dk, cs_ref[kvsl, :], -sn_ref[kvsl, :])
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, dout, rope_cs=None):
    q, k, v, out, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    rope = rope_cs is not None
    rope_specs = ([pl.BlockSpec((sk, d), lambda b, i: (0, 0))] * 2
                  if rope else [])
    rope_args = list(rope_cs) if rope else []
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, rope=rope),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ] + rope_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, dout, out, lse, *rope_args)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, rope=rope),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, sq), lambda b, i: (b, 0, 0)),
        ] + rope_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, dout, out, lse, *rope_args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_mha(q, k, v, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_mha_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_mha_bwd(scale, causal, block_q, block_k, res, dout):
    return _bwd(scale, causal, block_q, block_k, res, dout)


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


# ---------------------------------------------------------------------------
# rope-fused variant: q/k arrive PRE-rotary; the rotation happens in VMEM
# inside every kernel (and its transpose on the dq/dk gradients), so the
# roped q/k never round-trip through HBM. Analog of the reference's fused
# rope + attention ops (paddle/phi/kernels/fusion/gpu/fused_rope_*.cu,
# fused_multi_transformer_op.cu) — here it also shrinks the custom-vjp
# residuals to the raw projection outputs.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_mha_rope(q, k, v, c2, s2, scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k,
                  rope_cs=(c2, s2))
    return out


def _flash_mha_rope_fwd(q, k, v, c2, s2, scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k,
                    rope_cs=(c2, s2))
    return out, (q, k, v, out, lse, c2, s2)


def _flash_mha_rope_bwd(scale, causal, block_q, block_k, res, dout):
    q, k, v, out, lse, c2, s2 = res
    dq, dk, dv = _bwd(scale, causal, block_q, block_k,
                      (q, k, v, out, lse), dout, rope_cs=(c2, s2))
    return dq, dk, dv, jnp.zeros_like(c2), jnp.zeros_like(s2)


_flash_mha_rope.defvjp(_flash_mha_rope_fwd, _flash_mha_rope_bwd)


from ...core.dispatch import op as _op


@_op("flash_attention_pallas")
def _flash_attention_arrays(q, k, v, causal=True, scale=None):
    """q/k/v: [B, S, H, D] (paddle layout). GQA: kv heads broadcast."""
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2).reshape(b * hq, sq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * hq, k.shape[1], d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * hq, v.shape[1], d)
    bq, bk = _block_sizes(sq, kt.shape[1])
    out = _flash_mha(qt, kt, vt, float(s), bool(causal), bq, bk)
    return jnp.swapaxes(out.reshape(b, hq, sq, d), 1, 2)


def flash_attention_fwd(q, k, v, causal=True, scale=None):
    """Tensor-level entry used by nn.functional (dispatch wraps autograd)."""
    return _flash_attention_arrays(q, k, v, causal=bool(causal), scale=scale)


def _widen_tables(cos, sin):
    """[S, D/2] rope tables -> full-width [S, D] f32 (both halves)."""
    return (jnp.concatenate([cos, cos], axis=-1).astype(jnp.float32),
            jnp.concatenate([sin, sin], axis=-1).astype(jnp.float32))


def _rope_widened(x, c2, s2):
    """Batched rope with full-width tables; x [..., S, D], c2/s2
    broadcastable [S, D]. Same half-split convention as _rot_f32 /
    models/llama.py:_rope_apply."""
    d2 = x.shape[-1] // 2
    rot = jnp.concatenate([-x[..., d2:], x[..., :d2]], axis=-1)
    return (x.astype(jnp.float32) * c2
            + rot.astype(jnp.float32) * s2).astype(x.dtype)


@_op("flash_attention_rope_pallas")
def _flash_attention_rope_arrays(q, k, v, cos, sin, causal=True, scale=None):
    """Rope-fused flash attention. q/k/v: [B, S, H, D] PRE-rotary;
    cos/sin: [S, D/2] rope tables (models/llama.py:_rope_cache layout)."""
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    c2, s2 = _widen_tables(cos, sin)
    qt = jnp.swapaxes(q, 1, 2).reshape(b * hq, sq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * hq, k.shape[1], d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * hq, v.shape[1], d)
    bq, bk = _block_sizes(sq, kt.shape[1])
    out = _flash_mha_rope(qt, kt, vt, c2, s2, float(s), bool(causal), bq, bk)
    return jnp.swapaxes(out.reshape(b, hq, sq, d), 1, 2)


def flash_attention_rope_fwd(q, k, v, cos, sin, causal=True, scale=None):
    """Tensor-level rope-fused entry used by nn.functional."""
    return _flash_attention_rope_arrays(q, k, v, cos, sin,
                                        causal=bool(causal), scale=scale)


@_op("attention_block_bhsd")
def _attention_block_bhsd(x, wq, wk, wv, wo, cos, sin, num_heads=1,
                          num_kv_heads=1, causal=True):
    """Whole attention block as ONE op with head-major internal layout:
    the projections produce [b, h, s, d] directly (einsum folds the head
    transpose into the matmul), rope applies in that layout, the kernel
    consumes a free reshape to [b*h, s, d], and the output projection
    contracts [b, h, s, d] straight back to [b, s, H] — the four 25 MB
    HBM transposes per layer of the [b, s, h, d] path never happen.

    Experimental (PT_ATTN_EINSUM=1): measured against the default path in
    PERF.md. x: [B, S, K]; wq/wk/wv: [K, H*D] or [K, Hkv*D]; wo: [H*D, K];
    cos/sin: [S, D/2]."""
    b, s, kdim = x.shape
    d = wq.shape[1] // num_heads
    wq4 = wq.reshape(kdim, num_heads, d)
    wk4 = wk.reshape(kdim, num_kv_heads, d)
    wv4 = wv.reshape(kdim, num_kv_heads, d)
    q = jnp.einsum("bsk,khd->bhsd", x, wq4)
    k = jnp.einsum("bsk,khd->bhsd", x, wk4)
    v = jnp.einsum("bsk,khd->bhsd", x, wv4)
    c2, s2 = _widen_tables(cos, sin)
    q = _rope_widened(q, c2, s2)
    k = _rope_widened(k, c2, s2)
    if num_kv_heads != num_heads:
        rep = num_heads // num_kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = 1.0 / math.sqrt(d)
    bq, bk = _block_sizes(s, s)
    out = _flash_mha(q.reshape(b * num_heads, s, d),
                     k.reshape(b * num_heads, s, d),
                     v.reshape(b * num_heads, s, d),
                     float(scale), bool(causal), bq, bk)
    out4 = out.reshape(b, num_heads, s, d)
    wo4 = wo.reshape(num_heads, d, kdim)
    return jnp.einsum("bhsd,hdk->bsk", out4, wo4)
