"""Fused residual-add + RMSNorm — Pallas TPU kernel.

Replaces the reference's fused norm family
(paddle/phi/kernels/gpu/rms_norm_kernel.cu, exposed as
paddle.incubate.nn.functional.fused_rms_norm, and the residual variants in
paddle/fluid/operators/fused/fused_dropout_helper.h) with a TPU-native
kernel that computes, in one HBM pass::

    resid = x + y                       # the new residual stream value
    out   = resid * rsqrt(mean(resid^2) + eps) * weight

returning (out, resid). The unfused XLA path materializes resid once for
the add and re-reads it for the norm; the kernel writes both outputs from
a single read of x and y.

Backward recomputes rsqrt from the saved bf16 ``resid`` (exactly what the
unfused path's norm does with the bf16 residual stream), so gradients match
the unfused composition bit-for-bit in expectation; dw reduces over rows in
XLA. Routing contract: hidden % 128 == 0, else callers fall back to the
jnp composition. Opt-in at the model level via ``PT_FUSED_NORM=1``
(measured on v5e before flipping any default — see PERF.md).

``fused_add_layer_norm`` is the same fusion for post-norm transformer
blocks (BERT/ERNIE): resid-add + mean/variance LayerNorm with weight+bias —
the direct analog of the reference's
paddle/fluid/operators/fused/fused_dropout_helper.h residual+LN epilogue.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _interpret, _pick_block

__all__ = ["fused_add_rms_norm", "fused_add_layer_norm",
           "use_fused_rms_norm"]


def use_fused_rms_norm():
    """One flag gates both fused-norm kernels (rms + layer)."""
    return os.environ.get("PT_FUSED_NORM", "0") == "1"


def _row_block(n_rows):
    return _pick_block("PT_RMSNORM_BR", 256, n_rows)


def _fwd_kernel(x_ref, y_ref, w_ref, out_ref, r_ref, *, eps):
    r = x_ref[...].astype(jnp.float32) + y_ref[...].astype(jnp.float32)
    r_ref[...] = r.astype(r_ref.dtype)
    # norm reads the bf16-rounded residual, matching the unfused composition
    rf = r_ref[...].astype(jnp.float32)
    ms = jnp.mean(rf * rf, axis=-1, keepdims=True)
    out = rf * jax.lax.rsqrt(ms + eps) * w_ref[...].astype(jnp.float32)
    out_ref[...] = out.astype(out_ref.dtype)


def _fwd(x, y, w, eps):
    rows, h = x.shape
    br = _row_block(rows)
    kern = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, h), x.dtype),
                   jax.ShapeDtypeStruct((rows, h), x.dtype)],
        interpret=_interpret(),
    )
    return kern(x, y, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_add_rms_norm(x, y, w, eps):
    out, r = _fwd(x, y, w, eps)
    return out, r


def _fused_fwd(x, y, w, eps):
    out, r = _fwd(x, y, w, eps)
    return (out, r), (r, w)


def _fused_bwd(eps, res, cts):
    r, w = res
    d_out, d_r = cts
    rf = r.astype(jnp.float32)
    g = d_out.astype(jnp.float32) * w.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(rf * rf, axis=-1, keepdims=True) + eps)
    dr = inv * g - rf * (inv ** 3) * jnp.mean(g * rf, axis=-1, keepdims=True)
    dr = dr + d_r.astype(jnp.float32)
    dw = jnp.sum(d_out.astype(jnp.float32) * rf * inv, axis=0,
                 keepdims=True)
    dx = dr.astype(r.dtype)
    return dx, dx, dw.astype(w.dtype)


_fused_add_rms_norm.defvjp(_fused_fwd, _fused_bwd)


def _fused_add_rms_norm_nd(x, y, weight, epsilon=1e-6):
    h = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for d in lead:
        rows *= d
    out, r = _fused_add_rms_norm(
        x.reshape(rows, h), y.reshape(rows, h), weight.reshape(1, h),
        float(epsilon))
    return out.reshape(*lead, h), r.reshape(*lead, h)


from ...core.dispatch import op as _op  # noqa: E402


@_op("fused_add_rms_norm_pallas")
def fused_add_rms_norm(x, y, weight, *, epsilon=1e-6):
    """(normed, resid) = RMSNorm(x + y) with one read of x and y.

    x, y: [..., hidden]; weight: [hidden]. Requires hidden % 128 == 0 (TPU
    lane tiling); callers check :func:`use_fused_rms_norm` and fall back to
    the jnp composition otherwise. Directly callable with jax arrays or
    framework Tensors (dispatch handles autograd either way).
    """
    return _fused_add_rms_norm_nd(x, y, weight, epsilon=float(epsilon))


# ---------------------------------------------------------------------------
# fused residual-add + LayerNorm (post-norm transformer epilogue)
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, y_ref, w_ref, b_ref, out_ref, r_ref, *, eps):
    r = x_ref[...].astype(jnp.float32) + y_ref[...].astype(jnp.float32)
    r_ref[...] = r.astype(r_ref.dtype)
    rf = r_ref[...].astype(jnp.float32)
    mu = jnp.mean(rf, axis=-1, keepdims=True)
    xc = rf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    out = (xc * jax.lax.rsqrt(var + eps) * w_ref[...].astype(jnp.float32)
           + b_ref[...].astype(jnp.float32))
    out_ref[...] = out.astype(out_ref.dtype)


def _ln_fwd(x, y, w, b, eps):
    rows, h = x.shape
    br = _row_block(rows)
    kern = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, h), x.dtype),
                   jax.ShapeDtypeStruct((rows, h), x.dtype)],
        interpret=_interpret(),
    )
    return kern(x, y, w, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_add_layer_norm(x, y, w, b, eps):
    return _ln_fwd(x, y, w, b, eps)


def _ln_vjp_fwd(x, y, w, b, eps):
    out, r = _ln_fwd(x, y, w, b, eps)
    return (out, r), (r, w)


def _ln_vjp_bwd(eps, res, cts):
    r, w = res
    d_out, d_r = cts
    rf = r.astype(jnp.float32)
    mu = jnp.mean(rf, axis=-1, keepdims=True)
    xc = rf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xc * inv
    g = d_out.astype(jnp.float32) * w.astype(jnp.float32)
    dr = inv * (g - jnp.mean(g, axis=-1, keepdims=True)
                - xhat * jnp.mean(g * xhat, axis=-1, keepdims=True))
    dr = dr + d_r.astype(jnp.float32)
    dw = jnp.sum(d_out.astype(jnp.float32) * xhat, axis=0, keepdims=True)
    db = jnp.sum(d_out.astype(jnp.float32), axis=0, keepdims=True)
    dx = dr.astype(r.dtype)
    return dx, dx, dw.astype(w.dtype), db.astype(w.dtype)


_fused_add_layer_norm.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def _fused_add_layer_norm_nd(x, y, weight, bias, epsilon=1e-12):
    h = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for d in lead:
        rows *= d
    out, r = _fused_add_layer_norm(
        x.reshape(rows, h), y.reshape(rows, h), weight.reshape(1, h),
        bias.reshape(1, h), float(epsilon))
    return out.reshape(*lead, h), r.reshape(*lead, h)


@_op("fused_add_layer_norm_pallas")
def fused_add_layer_norm(x, y, weight, bias, *, epsilon=1e-12):
    """(normed, resid) = LayerNorm(x + y) with one read of x and y.

    Post-norm transformer epilogue (BERT/ERNIE): only ``normed`` feeds the
    next sublayer, but ``resid`` is returned for parity with the rms
    variant. Same routing contract: hidden % 128 == 0.
    """
    return _fused_add_layer_norm_nd(x, y, weight, bias,
                                    epsilon=float(epsilon))
