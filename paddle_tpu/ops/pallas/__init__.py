"""Hand-written Pallas TPU kernels for the ops where XLA fusion isn't enough
— the TPU-native replacement for the reference's fused CUDA ops
(paddle/fluid/operators/fused/, paddle/phi/kernels/fusion/,
third_party/flashattn)."""
