"""Hand-written Pallas TPU kernels for the ops where XLA fusion isn't enough
— the TPU-native replacement for the reference's fused CUDA ops
(paddle/fluid/operators/fused/, paddle/phi/kernels/fusion/,
third_party/flashattn).

Kernels: flash_attention (plain + rope-fused), rms_norm (fused
residual-add + RMSNorm), moe_ffn (blockwise SwiGLU expert FFN). Each is
parity-tested in interpret mode (tests/test_pallas_*.py) and gated by an
opt-in env flag until an end-to-end win is measured on real hardware
(PERF.md records every verdict)."""
