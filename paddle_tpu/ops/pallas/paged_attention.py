"""Paged-attention decode kernel — Pallas TPU (ISSUE 7 tentpole, part b).

Single-token decode over a block-paged KV cache (PAPERS.md: "Ragged Paged
Attention: A High-Performance and Flexible LLM Inference Kernel for TPU").
Each grid step (request b, page p) DMAs ONE pool block — chosen by the
scalar-prefetched block table, so the gather never materializes the
per-request KV in HBM — and folds it into an online-softmax accumulator
held in VMEM scratch across the page loop. Ragged per-request lengths come
from the scalar-prefetched ``context_lens``: pages past a request's length
are skipped (``pl.when``), and the tail page masks positions beyond the
length, so ONE compiled kernel serves any mix of request lengths — the
whole point of the paged layout.

Layouts:
  q            [B, H, D]         (one decode token per request)
  k/v pool     [N, block, Hkv, D]
  block_tables [B * P] int32     (flattened; P = max pages per request)
  context_lens [B]     int32     (tokens INCLUDING the one just written)

GQA: kv heads are broadcast to q heads inside the kernel (VMEM-local
repeat, the pool stays at Hkv).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _interpret

__all__ = ["paged_decode_attention_pallas", "use_pallas_paged"]


def use_pallas_paged(head_dim, block_size):
    """The real-TPU gate: MXU-friendly head_dim and a lane-aligned block.
    Interpret mode (PT_PALLAS_INTERPRET=1) runs anywhere for parity tests."""
    if _interpret():
        return True
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return head_dim % 128 == 0 and block_size % 8 == 0


def _kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
            acc_ref, m_ref, l_ref, *, block_size, groups, scale):
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = lens_ref[pl.program_id(0)]
    n_pages = (ctx + block_size - 1) // block_size

    @pl.when(p < n_pages)
    def _page():
        q = q_ref[0].astype(jnp.float32) * scale          # [H, D]
        k = k_ref[0].astype(jnp.float32)                  # [block, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        kt = jnp.repeat(jnp.swapaxes(k, 0, 1), groups, axis=0)  # [H, blk, D]
        vt = jnp.repeat(jnp.swapaxes(v, 0, 1), groups, axis=0)
        s = jax.lax.dot_general(q, kt, (((1,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32)  # [H,blk]
        tok = p * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(tok < ctx, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        pexp = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * corr + jnp.sum(pexp, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            pexp, vt, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new
        # revisited output block: the LAST active page's write survives
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def paged_decode_attention_pallas(q, k_pool, v_pool, block_tables,
                                  context_lens, scale):
    """q [B, H, D]; pools [N, block, Hkv, D]; block_tables [B, P] int32;
    context_lens [B] int32. Returns [B, H, D]."""
    b, h, d = q.shape
    n, block_size, hkv, _ = k_pool.shape
    p = block_tables.shape[1]
    groups = h // hkv
    tables_flat = block_tables.reshape(-1).astype(jnp.int32)
    lens = context_lens.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, p),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, j, T, L: (i, 0, 0)),
            pl.BlockSpec((1, block_size, hkv, d),
                         lambda i, j, T, L: (T[i * p + j], 0, 0, 0)),
            pl.BlockSpec((1, block_size, hkv, d),
                         lambda i, j, T, L: (T[i * p + j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, j, T, L: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, block_size=block_size, groups=groups,
                          scale=float(scale)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=_interpret(),
    )(tables_flat, lens, q, k_pool, v_pool)
