"""Paged-attention decode kernel — Pallas TPU (ISSUE 7 tentpole, part b).

Single-token decode over a block-paged KV cache (PAPERS.md: "Ragged Paged
Attention: A High-Performance and Flexible LLM Inference Kernel for TPU").
Each grid step (request b, page p) DMAs ONE pool block — chosen by the
scalar-prefetched block table, so the gather never materializes the
per-request KV in HBM — and folds it into an online-softmax accumulator
held in VMEM scratch across the page loop. Ragged per-request lengths come
from the scalar-prefetched ``context_lens``: pages past a request's length
are skipped (``pl.when``), and the tail page masks positions beyond the
length, so ONE compiled kernel serves any mix of request lengths — the
whole point of the paged layout.

Layouts:
  q            [B, H, D]         (one decode token per request)
  k/v pool     [N, block, Hkv, D]
  block_tables [B * P] int32     (flattened; P = max pages per request)
  context_lens [B]     int32     (tokens INCLUDING the one just written)

GQA: kv heads are broadcast to q heads inside the kernel (VMEM-local
repeat, the pool stays at Hkv).

**Quantized pools (ISSUE 14, dequant-in-kernel):** with
``kv_dtype="int8"`` the pools hold int8 codes and two sidecar scale
pools ``[N, block, Hkv]`` f32 ride along. The kernels take two extra
scalar-prefetch-indexed operands — the scale rows of exactly the block
being DMA'd — and dequantize IN VMEM (``codes.astype(f32) *
scale[..., None]``) right before the existing online-softmax fold, so
HBM traffic per page drops ~4x while the attention math past the
dequant is bit-identical to the fp kernel fed the dequantized values.
The lax fallback in ``inference/serving/paged_attention.py`` mirrors
the same gather + multiply, so CPU tier-1 tests the same semantics.
Scale operands use an Hkv-lane layout — fine in interpret mode and on
CPU; a real-TPU deployment at MXU widths would pad the scale lane dim
to the tile boundary (the gate below already restricts the real-TPU
path to MXU-friendly shapes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _interpret

__all__ = ["paged_decode_attention_pallas",
           "paged_multiquery_attention_pallas", "use_pallas_paged"]


def use_pallas_paged(head_dim, block_size):
    """The real-TPU gate: MXU-friendly head_dim and a lane-aligned block.
    Interpret mode (PT_PALLAS_INTERPRET=1) runs anywhere for parity tests."""
    if _interpret():
        return True
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return head_dim % 128 == 0 and block_size % 8 == 0


def _kernel(tables_ref, lens_ref, *refs, block_size, groups, scale,
            quantized=False):
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = lens_ref[pl.program_id(0)]
    n_pages = (ctx + block_size - 1) // block_size

    @pl.when(p < n_pages)
    def _page():
        q = q_ref[0].astype(jnp.float32) * scale          # [H, D]
        k = k_ref[0].astype(jnp.float32)                  # [block, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            # dequant-in-kernel: the DMA'd block is int8 codes; its scale
            # rows [block, Hkv] ride in as scalar-prefetch-indexed
            # operands and the multiply happens here in VMEM — HBM never
            # sees a dequantized page
            k = k * ks_ref[0][..., None]
            v = v * vs_ref[0][..., None]
        kt = jnp.repeat(jnp.swapaxes(k, 0, 1), groups, axis=0)  # [H, blk, D]
        vt = jnp.repeat(jnp.swapaxes(v, 0, 1), groups, axis=0)
        s = jax.lax.dot_general(q, kt, (((1,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32)  # [H,blk]
        tok = p * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(tok < ctx, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        pexp = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * corr + jnp.sum(pexp, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            pexp, vt, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new
        # revisited output block: the LAST active page's write survives
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def paged_decode_attention_pallas(q, k_pool, v_pool, block_tables,
                                  context_lens, scale,
                                  k_scale=None, v_scale=None):
    """q [B, H, D]; pools [N, block, Hkv, D]; block_tables [B, P] int32;
    context_lens [B] int32. Returns [B, H, D]. With int8 pools,
    ``k_scale``/``v_scale`` [N, block, Hkv] f32 arm dequant-in-kernel."""
    b, h, d = q.shape
    n, block_size, hkv, _ = k_pool.shape
    p = block_tables.shape[1]
    groups = h // hkv
    quantized = k_scale is not None
    tables_flat = block_tables.reshape(-1).astype(jnp.int32)
    lens = context_lens.astype(jnp.int32)

    in_specs = [
        pl.BlockSpec((1, h, d), lambda i, j, T, L: (i, 0, 0)),
        pl.BlockSpec((1, block_size, hkv, d),
                     lambda i, j, T, L: (T[i * p + j], 0, 0, 0)),
        pl.BlockSpec((1, block_size, hkv, d),
                     lambda i, j, T, L: (T[i * p + j], 0, 0, 0)),
    ]
    operands = [q, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_size, hkv),
                         lambda i, j, T, L: (T[i * p + j], 0, 0)),
            pl.BlockSpec((1, block_size, hkv),
                         lambda i, j, T, L: (T[i * p + j], 0, 0)),
        ]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, p),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda i, j, T, L: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, block_size=block_size, groups=groups,
                          scale=float(scale), quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=_interpret(),
    )(tables_flat, lens, *operands)


def _mq_kernel(tables_ref, lens_ref, starts_ref, *refs, block_size,
               groups, t_q, scale, quantized=False):
    """Multi-query variant (ISSUE 11): T query rows per request folded
    into the accumulator's leading dim ([T*H, D]), per-row causal masking
    against the row's absolute position ``start + t``. Same one-block-DMA-
    per-grid-step structure as the decode kernel (CuBridge's iterate-on-
    the-verify-kernel guidance, PAPERS.md). ``quantized`` dequantizes the
    DMA'd int8 block in VMEM from its sidecar scale rows (ISSUE 14)."""
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    b = pl.program_id(0)
    ctx = lens_ref[b]
    start = starts_ref[b]
    n_pages = (ctx + block_size - 1) // block_size

    @pl.when(p < n_pages)
    def _page():
        h = q_ref.shape[2]
        q = q_ref[0].astype(jnp.float32) * scale          # [T, H, D]
        q2 = q.reshape(t_q * h, q.shape[-1])              # [T*H, D]
        k = k_ref[0].astype(jnp.float32)                  # [block, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0][..., None]
            v = v * vs_ref[0][..., None]
        kt = jnp.repeat(jnp.swapaxes(k, 0, 1), groups, axis=0)  # [H, blk, D]
        vt = jnp.repeat(jnp.swapaxes(v, 0, 1), groups, axis=0)
        # scores per (row=t*H+h, token-in-block): contract D against the
        # row's head slice of this page
        s = jax.lax.dot_general(
            q2.reshape(t_q, h, -1), kt, (((2,), (2,)), ((1,), (0,))),
        )                                                  # [H, T, blk]
        s = jnp.swapaxes(s, 0, 1).reshape(t_q * h, block_size)
        tok = p * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        row_t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // h
        ok = (tok <= start + row_t) & (tok < ctx)
        s = jnp.where(ok, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(ok, pexp, 0.0)  # rows with no visible token yet
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * corr + jnp.sum(pexp, axis=1, keepdims=True)
        av = jax.lax.dot_general(
            pexp.reshape(t_q, h, block_size), vt,
            (((2,), (1,)), ((1,), (0,))))                  # [H, T, D]
        acc_ref[...] = acc_ref[...] * corr + \
            jnp.swapaxes(av, 0, 1).reshape(t_q * h, -1)
        m_ref[...] = m_new
        o_ref[0] = (acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
                    ).reshape(t_q, h, -1).astype(o_ref.dtype)


def paged_multiquery_attention_pallas(q, k_pool, v_pool, block_tables,
                                      context_lens, q_start, scale,
                                      k_scale=None, v_scale=None):
    """q [B, T, H, D] at absolute positions ``q_start[b] + t``; pools
    [N, block, Hkv, D]; block_tables [B, P] int32; context_lens [B] int32
    (visible tokens including the last real query row). Returns
    [B, T, H, D]; rows past ``context_lens - q_start`` are padding and
    undefined. With int8 pools, ``k_scale``/``v_scale`` [N, block, Hkv]
    f32 arm dequant-in-kernel."""
    b, t, h, d = q.shape
    n, block_size, hkv, _ = k_pool.shape
    p = block_tables.shape[1]
    groups = h // hkv
    quantized = k_scale is not None
    tables_flat = block_tables.reshape(-1).astype(jnp.int32)
    lens = context_lens.astype(jnp.int32)
    starts = q_start.astype(jnp.int32)

    in_specs = [
        pl.BlockSpec((1, t, h, d), lambda i, j, T, L, S: (i, 0, 0, 0)),
        pl.BlockSpec((1, block_size, hkv, d),
                     lambda i, j, T, L, S: (T[i * p + j], 0, 0, 0)),
        pl.BlockSpec((1, block_size, hkv, d),
                     lambda i, j, T, L, S: (T[i * p + j], 0, 0, 0)),
    ]
    operands = [q, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_size, hkv),
                         lambda i, j, T, L, S: (T[i * p + j], 0, 0)),
            pl.BlockSpec((1, block_size, hkv),
                         lambda i, j, T, L, S: (T[i * p + j], 0, 0)),
        ]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, p),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, t, h, d),
                               lambda i, j, T, L, S: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t * h, d), jnp.float32),
            pltpu.VMEM((t * h, 1), jnp.float32),
            pltpu.VMEM((t * h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_mq_kernel, block_size=block_size, groups=groups,
                          t_q=t, scale=float(scale), quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, t, h, d), q.dtype),
        interpret=_interpret(),
    )(tables_flat, lens, starts, *operands)
