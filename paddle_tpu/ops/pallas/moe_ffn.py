"""Blockwise MoE expert FFN — Pallas TPU kernel.

The SURVEY §7.1 "MoE dispatch" kernel, scoped the TPU-native way: the
dispatch/combine scatter-gathers are already XLA's strength (sort-free
one-hot/scatter lowering; under GSPMD they become the all_to_all the
reference's global_scatter/global_gather collective ops implement by hand —
paddle/fluid/operators/collective/global_scatter_op.*). What XLA does NOT do
for the expert computation is avoid materializing the [E, C, I] SwiGLU
intermediates in HBM (I = intermediate ≈ 4h, so that round-trip is the
dominant MoE memory traffic). This kernel computes, per (expert, token
block), the full SwiGLU FFN

    out = (silu(x @ Wg) * (x @ Wu)) @ Wd

with the [bc, bi] intermediates living only in VMEM, accumulating the down
projection across I tiles in an f32 output block. Backward is
recompute-style in XLA (same policy as ops/pallas/rms_norm.py: the fwd
kernel saves only the inputs).

Routing contract: h % 128 == 0 and I % 128 == 0; callers fall back to the
einsum composition otherwise. Opt-in via ``PT_FUSED_MOE=1`` (measure before
flipping any default — PERF.md).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _interpret, _pick_block

__all__ = ["moe_expert_ffn", "use_fused_moe_ffn", "moe_ffn_shapes_ok"]


def use_fused_moe_ffn():
    return os.environ.get("PT_FUSED_MOE", "0") == "1"


def moe_ffn_shapes_ok(h, i):
    return h % 128 == 0 and i % 128 == 0


def _blocks(c, i):
    return (_pick_block("PT_MOE_BC", 256, c),
            _pick_block("PT_MOE_BI", 512, i, floor=128))


def _ffn_kernel(x_ref, gw_ref, uw_ref, dw_ref, out_ref):
    it = pl.program_id(2)
    x = x_ref[0].astype(jnp.float32)                       # [bc, h]
    g = jax.lax.dot(x, gw_ref[0].astype(jnp.float32))      # [bc, bi]
    u = jax.lax.dot(x, uw_ref[0].astype(jnp.float32))
    act = jax.nn.silu(g) * u
    part = jax.lax.dot(act, dw_ref[0].astype(jnp.float32))  # [bc, h]

    @pl.when(it == 0)
    def _init():
        out_ref[0] = part

    @pl.when(it > 0)
    def _acc():
        out_ref[0] += part


def _ffn_fwd_arrays(x, gate_w, up_w, down_w):
    e, c, h = x.shape
    i = gate_w.shape[-1]
    bc, bi = _blocks(c, i)
    out = pl.pallas_call(
        _ffn_kernel,
        grid=(e, c // bc, i // bi),
        in_specs=[
            pl.BlockSpec((1, bc, h), lambda ei, ci, ii: (ei, ci, 0)),
            pl.BlockSpec((1, h, bi), lambda ei, ci, ii: (ei, 0, ii)),
            pl.BlockSpec((1, h, bi), lambda ei, ci, ii: (ei, 0, ii)),
            pl.BlockSpec((1, bi, h), lambda ei, ci, ii: (ei, ii, 0)),
        ],
        out_specs=pl.BlockSpec((1, bc, h), lambda ei, ci, ii: (ei, ci, 0)),
        out_shape=jax.ShapeDtypeStruct((e, c, h), jnp.float32),
        interpret=_interpret(),
    )(x, gate_w, up_w, down_w)
    return out.astype(x.dtype)


@jax.custom_vjp
def moe_expert_ffn(x, gate_w, up_w, down_w):
    """SwiGLU expert FFN over dispatched tokens.

    x: [E, C, h]; gate_w/up_w: [E, h, I]; down_w: [E, I, h] → [E, C, h],
    without HBM-materializing the [E, C, I] intermediates.
    """
    return _ffn_fwd_arrays(x, gate_w, up_w, down_w)


def _ffn_fwd(x, gate_w, up_w, down_w):
    return _ffn_fwd_arrays(x, gate_w, up_w, down_w), (x, gate_w, up_w, down_w)


def _ffn_bwd(res, dout):
    x, gate_w, up_w, down_w = res
    xf = x.astype(jnp.float32)
    do = dout.astype(jnp.float32)
    g = jnp.einsum("ech,ehi->eci", xf, gate_w.astype(jnp.float32))
    u = jnp.einsum("ech,ehi->eci", xf, up_w.astype(jnp.float32))
    sg = jax.nn.sigmoid(g)
    s = g * sg                                  # silu(g)
    act = s * u
    d_act = jnp.einsum("ech,eih->eci", do, down_w.astype(jnp.float32))
    d_down = jnp.einsum("eci,ech->eih", act, do)
    du = d_act * s
    ds = d_act * u
    dg = ds * (sg * (1.0 + g * (1.0 - sg)))     # d silu
    dx = (jnp.einsum("eci,ehi->ech", dg, gate_w.astype(jnp.float32))
          + jnp.einsum("eci,ehi->ech", du, up_w.astype(jnp.float32)))
    d_gate = jnp.einsum("ech,eci->ehi", xf, dg)
    d_up = jnp.einsum("ech,eci->ehi", xf, du)
    return (dx.astype(x.dtype), d_gate.astype(gate_w.dtype),
            d_up.astype(up_w.dtype), d_down.astype(down_w.dtype))


moe_expert_ffn.defvjp(_ffn_fwd, _ffn_bwd)
