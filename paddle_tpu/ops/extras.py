"""Long-tail tensor-API parity ops.

Reference: the remaining ``python/paddle/__init__.py`` ``__all__`` surface —
tensor/manipulation.py (stacks, unfold, scatter family), tensor/math.py
(distance/special functions), tensor/creation.py (index grids, vander,
complex), tensor/attribute.py (shape/rank/is_*), tensor/random.py
(binomial/poisson). Each is a pure-JAX op on the dispatch layer; anything
shape-dynamic is documented as such.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.dispatch import op
from ..core.tensor import Tensor

__all__ = [
    "hstack", "vstack", "dstack", "column_stack", "reverse", "take",
    "unflatten", "unfold", "multiplex", "shape", "rank", "broadcast_shape",
    "scatter_nd", "diag_embed", "diagonal_scatter", "select_scatter",
    "slice_scatter", "masked_scatter", "index_fill", "tril_indices",
    "triu_indices", "vander", "complex", "polar", "mv", "dist", "cdist",
    "pdist", "sgn", "signbit", "logit", "frexp", "ldexp", "i0e", "i1",
    "i1e", "polygamma", "multigammaln", "nanmedian", "nanquantile",
    "logcumsumexp", "cummin", "trapezoid", "cumulative_trapezoid", "renorm",
    "add_n", "binomial", "poisson", "combinations", "is_complex",
    "is_floating_point", "is_integer", "finfo", "iinfo", "inverse",
    "top_p_sampling",
]


def _ints(v):
    if isinstance(v, Tensor):
        v = v.tolist()
    if isinstance(v, (int, np.integer)):
        return int(v)
    return tuple(int(x) for x in v)


# ---------------------------------------------------------------------------
# stacks / layout (reference tensor/manipulation.py)
# ---------------------------------------------------------------------------

@op("hstack_n")
def _hstack(*xs):
    return jnp.hstack(xs)


def hstack(x, name=None):
    return _hstack(*x)


@op("vstack_n")
def _vstack(*xs):
    return jnp.vstack(xs)


def vstack(x, name=None):
    return _vstack(*x)


@op("dstack_n")
def _dstack(*xs):
    return jnp.dstack(xs)


def dstack(x, name=None):
    return _dstack(*x)


@op("column_stack_n")
def _column_stack(*xs):
    return jnp.column_stack(xs)


def column_stack(x, name=None):
    return _column_stack(*x)


def reverse(x, axis, name=None):
    """Alias of flip (reference keeps both)."""
    from .manipulation import flip

    return flip(x, axis)


@op("take")
def _take(x, index, mode="raise"):
    flat = x.reshape(-1)
    idx = index.astype(jnp.int32)
    n = flat.shape[0]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    else:  # 'raise' cannot raise inside jit; clip like 'clip'
        idx = jnp.clip(idx, -n, n - 1)
    idx = jnp.where(idx < 0, idx + n, idx)
    return flat[idx]


def take(x, index, mode="raise", name=None):
    return _take(x, index, mode=mode)


@op("unflatten")
def _unflatten(x, axis=0, sizes=()):
    s = list(x.shape)
    return jnp.reshape(x, tuple(s[:axis]) + tuple(sizes)
                       + tuple(s[axis + 1:]))


def unflatten(x, axis, shape, name=None):
    axis = int(axis) % x.ndim
    return _unflatten(x, axis=axis, sizes=_ints(shape))


@op("tensor_unfold")
def _unfold(x, axis=0, size=1, step=1):
    length = x.shape[axis]
    n_win = (length - size) // step + 1
    xm = jnp.moveaxis(x, axis, -1)
    idx = (jnp.arange(n_win)[:, None] * step
           + jnp.arange(size)[None, :])            # [n_win, size]
    win = xm[..., idx]                              # [..., n_win, size]
    return jnp.moveaxis(win, -2, axis)


def unfold(x, axis, size, step, name=None):
    """Sliding windows over ``axis``: that dim becomes n_windows and a new
    trailing dim of length ``size`` is appended (reference Tensor.unfold)."""
    return _unfold(x, axis=int(axis) % x.ndim, size=int(size),
                   step=int(step))


@op("multiplex")
def _multiplex(index, *ins):
    stacked = jnp.stack(ins, axis=0)                # [K, N, ...]
    idx = index.reshape(-1).astype(jnp.int32)       # [N]
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


def multiplex(inputs, index, name=None):
    return _multiplex(index, *inputs)


def shape(x, name=None):
    """1-D int32 tensor of the (static) shape (reference paddle.shape)."""
    return Tensor(np.asarray(x.shape, np.int32))


def rank(x, name=None):
    return Tensor(np.asarray(len(x.shape), np.int32))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@op("scatter_nd")
def _scatter_nd(index, updates, out_shape=()):
    zeros = jnp.zeros(out_shape, updates.dtype)
    return zeros.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def scatter_nd(index, updates, shape, name=None):
    return _scatter_nd(index, updates, out_shape=_ints(shape))


# ---------------------------------------------------------------------------
# scatter family (reference tensor/manipulation.py select_scatter etc.)
# ---------------------------------------------------------------------------

@op("diag_embed")
def _diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    ii = jnp.arange(x.shape[-1])
    r = ii + max(-offset, 0)
    c = ii + max(offset, 0)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    out = out.at[..., r, c].set(x)
    return jnp.moveaxis(out, (-2, -1), (dim1, dim2))


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    nd = x.ndim + 1
    return _diag_embed(x, offset=int(offset), dim1=int(dim1) % nd,
                       dim2=int(dim2) % nd)


@op("diagonal_scatter")
def _diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    xm = jnp.moveaxis(x, (axis1, axis2), (-2, -1))
    n = y.shape[-1]
    r = jnp.arange(n) + max(-offset, 0)
    c = jnp.arange(n) + max(offset, 0)
    xm = xm.at[..., r, c].set(y)
    return jnp.moveaxis(xm, (-2, -1), (axis1, axis2))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    return _diagonal_scatter(x, y, offset=int(offset),
                             axis1=int(axis1) % x.ndim,
                             axis2=int(axis2) % x.ndim)


@op("select_scatter")
def _select_scatter(x, value, axis=0, index=0):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(value.astype(x.dtype))


def select_scatter(x, values, axis, index, name=None):
    return _select_scatter(x, values, axis=int(axis) % x.ndim,
                           index=int(index))


@op("slice_scatter")
def _slice_scatter(x, value, axes=(), starts=(), ends=(), strides=()):
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return x.at[tuple(idx)].set(value.astype(x.dtype))


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    return _slice_scatter(x, value, axes=_ints(axes), starts=_ints(starts),
                          ends=_ints(ends), strides=_ints(strides))


@op("masked_scatter")
def _masked_scatter(x, mask, value):
    m = jnp.broadcast_to(mask, x.shape).reshape(-1)
    vflat = value.reshape(-1)
    # k-th True position takes value[k]; static-shape friendly form
    pos = jnp.cumsum(m.astype(jnp.int32)) - 1
    take_v = vflat[jnp.clip(pos, 0, vflat.shape[0] - 1)]
    return jnp.where(m, take_v.astype(x.dtype),
                     x.reshape(-1)).reshape(x.shape)


def masked_scatter(x, mask, value, name=None):
    return _masked_scatter(x, mask, value)


@op("index_fill")
def _index_fill(x, index, axis=0, fill_value=0.0):
    idx = [slice(None)] * x.ndim
    idx[axis] = index.astype(jnp.int32)
    return x.at[tuple(idx)].set(jnp.asarray(fill_value, x.dtype))


def index_fill(x, index, axis, value, name=None):
    if isinstance(value, Tensor):
        value = value.item()
    return _index_fill(x, index, axis=int(axis) % x.ndim,
                       fill_value=float(value))


# ---------------------------------------------------------------------------
# creation extras (reference tensor/creation.py)
# ---------------------------------------------------------------------------

def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.tril_indices(int(row), int(offset), int(col))
    return Tensor(np.stack([r, c]).astype(dtypes.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(int(row), int(offset), int(col))
    return Tensor(np.stack([r, c]).astype(dtypes.convert_dtype(dtype)))


@op("vander")
def _vander(x, n=0, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


def vander(x, n=None, increasing=False, name=None):
    n = x.shape[0] if n is None else int(n)
    return _vander(x, n=n, increasing=bool(increasing))


@op("make_complex")
def _complex(real, imag):
    return jax.lax.complex(real.astype(jnp.float32),
                           imag.astype(jnp.float32))


def complex(real, imag, name=None):  # noqa: A001 - reference name
    return _complex(real, imag)


@op("polar")
def _polar(absv, angle):
    return jax.lax.complex(absv * jnp.cos(angle), absv * jnp.sin(angle))


def polar(abs, angle, name=None):  # noqa: A002 - reference signature
    return _polar(abs, angle)


# ---------------------------------------------------------------------------
# math extras (reference tensor/math.py, tensor/linalg.py)
# ---------------------------------------------------------------------------

@op("mv")
def _mv(x, vec):
    return x @ vec


def mv(x, vec, name=None):
    return _mv(x, vec)


@op("dist")
def _dist(x, y, p=2.0):
    d = (x - y).reshape(-1)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == 0:
        return jnp.sum(d != 0).astype(d.dtype)
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


def dist(x, y, p=2, name=None):
    return _dist(x, y, p=float(p))


@op("cdist")
def _cdist(x, y, p=2.0):
    diff = jnp.abs(x[..., :, None, :] - y[..., None, :, :])
    if p == float("inf"):
        return jnp.max(diff, axis=-1)
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1))
    return jnp.sum(diff ** p, axis=-1) ** (1.0 / p)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    return _cdist(x, y, p=float(p))


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances (upper triangle, row-major order)."""
    full = cdist(x, x, p=p)
    r, c = np.triu_indices(int(x.shape[0]), 1)
    from .manipulation import gather_nd

    idx = Tensor(np.stack([r, c], axis=1).astype(np.int64))
    return gather_nd(full, idx)


@op("sgn")
def _sgn(x):
    if jnp.iscomplexobj(x):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0.0 + 0.0j, x / jnp.where(mag == 0, 1.0,
                                                             mag))
    return jnp.sign(x)


def sgn(x, name=None):
    return _sgn(x)


@op("signbit")
def _signbit(x):
    return jnp.signbit(x)


def signbit(x, name=None):
    return _signbit(x)


@op("logit")
def _logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x) - jnp.log1p(-x)


def logit(x, eps=None, name=None):
    return _logit(x, eps=None if eps is None else float(eps))


@op("frexp", differentiable=False)
def _frexp(x):
    m, e = jnp.frexp(x)
    return m, e.astype(jnp.int32)


def frexp(x, name=None):
    return _frexp(x)


@op("ldexp")
def _ldexp(x, y):
    return x * (2.0 ** y.astype(jnp.float32))


def ldexp(x, y, name=None):
    return _ldexp(x, y)


def _special(name, fn):
    fwd = op(name)(fn)

    def public(x, name=None):
        return fwd(x)

    public.__name__ = name
    return public


i0e = _special("i0e", lambda x: jax.scipy.special.i0e(x))
i1 = _special("i1", lambda x: jax.scipy.special.i1(x))
i1e = _special("i1e", lambda x: jax.scipy.special.i1e(x))


@op("polygamma")
def _polygamma(x, n=1):
    return jax.scipy.special.polygamma(n, x)


def polygamma(x, n, name=None):
    return _polygamma(x, n=int(n))


@op("multigammaln")
def _multigammaln(x, p=1):
    return jax.scipy.special.multigammaln(x, p)


def multigammaln(x, p, name=None):
    return _multigammaln(x, p=int(p))


@op("nanmedian")
def _nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    from .math import _axis

    return _nanmedian(x, axis=_axis(axis), keepdim=bool(keepdim))


@op("nanquantile")
def _nanquantile(x, q=0.5, axis=None, keepdim=False):
    return jnp.nanquantile(x.astype(jnp.float32), q, axis=axis,
                           keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    from .math import _axis

    if isinstance(q, Tensor):
        q = q.tolist()
    return _nanquantile(x, q=q, axis=_axis(axis), keepdim=bool(keepdim))


@op("logcumsumexp")
def _logcumsumexp(x, axis=-1):
    return jax.lax.cumlogsumexp(x, axis=axis)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    if axis is None:
        return _logcumsumexp(x.reshape(-1), axis=0)
    return _logcumsumexp(x, axis=int(axis))


@op("cummin_vals")
def _cummin(x, axis=-1):
    return jax.lax.cummin(x, axis=axis)


def cummin(x, axis=None, dtype="int64", name=None):
    """Values-only (matching this repo's cummax; the reference also returns
    argmin indices)."""
    if axis is None:
        return _cummin(x.reshape(-1), axis=0)
    return _cummin(x, axis=int(axis))


@op("trapezoid")
def _trapezoid(y, x=None, dx=1.0, axis=-1):
    return jnp.trapezoid(y, x=x, dx=dx, axis=axis)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    return _trapezoid(y, x, dx=1.0 if dx is None else float(dx),
                      axis=int(axis))


@op("cumulative_trapezoid")
def _cumulative_trapezoid(y, x=None, dx=1.0, axis=-1):
    ym = jnp.moveaxis(y, axis, -1)
    avg = (ym[..., 1:] + ym[..., :-1]) / 2.0
    if x is not None:
        xm = x if x.ndim == 1 else jnp.moveaxis(x, axis, -1)
        avg = avg * jnp.diff(xm, axis=-1)
    else:
        avg = avg * dx
    return jnp.moveaxis(jnp.cumsum(avg, axis=-1), -1, axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    return _cumulative_trapezoid(y, x, dx=1.0 if dx is None else float(dx),
                                 axis=int(axis))


@op("renorm")
def _renorm(x, p=2.0, axis=0, max_norm=1.0):
    xm = jnp.moveaxis(x, axis, 0).reshape(x.shape[axis], -1)
    norms = jnp.sum(jnp.abs(xm) ** p, axis=1) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return x * scale.reshape(shape).astype(x.dtype)


def renorm(x, p, axis, max_norm, name=None):
    return _renorm(x, p=float(p), axis=int(axis) % x.ndim,
                   max_norm=float(max_norm))


@op("add_n")
def _add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return _add_n(*inputs)


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools

    n = int(x.shape[0])
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    idx = np.asarray(list(gen(range(n), int(r))), np.int64)
    if idx.size == 0:
        idx = idx.reshape(0, int(r))
    from .manipulation import index_select

    return index_select(x, Tensor(idx.reshape(-1)), axis=0).reshape(
        [idx.shape[0], int(r)] + list(x.shape[1:]))


# ---------------------------------------------------------------------------
# random (reference tensor/random.py)
# ---------------------------------------------------------------------------

def binomial(count, prob, name=None):
    from ..core import rng

    key = rng.next_key()
    c = count._data if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._data if isinstance(prob, Tensor) else jnp.asarray(prob)
    out = jax.random.binomial(key, c.astype(jnp.float32),
                              p.astype(jnp.float32))
    # int64 truncates to int32 without x64 mode; stay in the native width
    return Tensor(out.astype(jnp.int32))


def poisson(x, name=None):
    from ..core import rng

    key = rng.next_key()
    lam = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(key, lam).astype(lam.dtype))


# ---------------------------------------------------------------------------
# dtype attributes (reference tensor/attribute.py + framework/dtype.py)
# ---------------------------------------------------------------------------

def is_complex(x):
    d = x._data.dtype if hasattr(x, "_data") else np.dtype(x)
    return jnp.issubdtype(d, jnp.complexfloating)


def is_floating_point(x):
    d = x._data.dtype if hasattr(x, "_data") else np.dtype(x)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(x):
    d = x._data.dtype if hasattr(x, "_data") else np.dtype(x)
    return jnp.issubdtype(d, jnp.integer)


def finfo(dtype):
    import ml_dtypes

    return ml_dtypes.finfo(dtypes.convert_dtype(dtype))


def iinfo(dtype):
    return np.iinfo(dtypes.convert_dtype(dtype))


def inverse(x, name=None):
    """Alias of linalg.inv (reference tensor/math.py inverse)."""
    from .linalg import inv

    return inv(x)


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """Nucleus (top-p) sampling per row (reference tensor/random.py
    top_p_sampling over the fused CUDA kernel): keep the smallest prefix
    of descending-probability tokens whose mass exceeds ``ps``, renormalize,
    sample one. Returns (values, ids)."""
    import jax

    from ..core import rng

    probs = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    p_arr = ps._data if isinstance(ps, Tensor) else jnp.asarray(ps)
    p_arr = jnp.reshape(p_arr, (-1, 1)).astype(jnp.float32)
    sort_idx = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    # keep tokens whose preceding mass < ps (always keep the first)
    keep = (cum - sorted_p) < p_arr
    filt = jnp.where(keep, sorted_p, 0.0)
    filt = filt / jnp.sum(filt, axis=-1, keepdims=True)
    key = rng.next_key() if seed < 0 else jax.random.PRNGKey(int(seed))
    choice = jax.random.categorical(key, jnp.log(filt + 1e-30), axis=-1)
    ids = jnp.take_along_axis(sort_idx, choice[:, None], axis=-1)
    vals = jnp.take_along_axis(probs, ids, axis=-1)
    return Tensor(vals), Tensor(ids.astype(jnp.int64))


__all__ += ["sinc", "sinc_", "igamma", "igammac", "log_normal",
            "standard_gamma"]


@op("sinc")
def sinc(x, name=None):
    """Normalized sinc: sin(pi x)/(pi x), 1 at x = 0 (paddle.sinc; newer
    than this reference snapshot — kept for tensor-API parity with current
    paddle)."""
    x = jnp.asarray(x)
    return jnp.sinc(x.astype(jnp.result_type(x, jnp.float32)))


@op("igamma")
def igamma(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y) (paddle.igamma
    convention: x is the shape parameter, y the integral's lower limit)."""
    import jax.scipy.special as jss

    x = jnp.asarray(x)
    f = jnp.result_type(x, jnp.float32)
    return jss.gammaincc(x.astype(f), jnp.asarray(y).astype(f))


@op("igammac")
def igammac(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) — the complement of
    :func:`igamma` (paddle.igammac convention)."""
    import jax.scipy.special as jss

    x = jnp.asarray(x)
    f = jnp.result_type(x, jnp.float32)
    return jss.gammainc(x.astype(f), jnp.asarray(y).astype(f))


# mean/std travel as ARRAY args (not closure state): the dispatch layer's
# jit cache keys on (op name, static kwargs), so anything value-like must be
# an operand or successive calls would replay the first call's closure
@op("log_normal_sample")
def _log_normal_sample(key, mean, std, shape=()):
    return jnp.exp(mean + std * jax.random.normal(key, tuple(shape)))


def log_normal(mean=1.0, std=2.0, shape=None, dtype=None, name=None):
    """Sample exp(N(mean, std^2)) (paddle.log_normal; tensor/random.py
    family). ``mean``/``std`` parameterize the UNDERLYING normal."""
    from ..core import rng

    shape = [1] if shape is None else [int(s) for s in shape]
    out = _log_normal_sample(rng.next_key(),
                             jnp.float32(mean), jnp.float32(std),
                             shape=tuple(shape))
    return out.astype(dtype) if dtype is not None else out


@op("standard_gamma_sample")
def _standard_gamma_sample(x, key):
    return jax.random.gamma(key, jnp.asarray(x))


def standard_gamma(x, name=None):
    """Sample Gamma(shape=x, scale=1) elementwise (paddle.standard_gamma,
    tensor/random.py family)."""
    from ..core import rng

    return _standard_gamma_sample(x, rng.next_key())


def sinc_(x, name=None):
    """In-place sinc (paddle.sinc_)."""
    out = sinc(x)
    x._data = out._data if isinstance(out, Tensor) else out
    return x


__all__ += ["bernoulli_", "log_normal_"]


def bernoulli_(x, p=0.5, name=None):
    """In-place Bernoulli re-init (paddle.bernoulli_; tensor/random.py
    family): x <- Bernoulli(p) sample of x's shape/dtype."""
    return Tensor.bernoulli_(x, p=p)


def log_normal_(x, mean=1.0, std=2.0, name=None):
    """In-place log-normal re-init (paddle.log_normal_): x <-
    exp(N(mean, std^2)) sample of x's shape/dtype."""
    return Tensor.log_normal_(x, mean=mean, std=std)
