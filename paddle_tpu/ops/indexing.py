"""__getitem__/__setitem__ support.

Reference: the pybind slice machinery in paddle/fluid/pybind/eager_method.cc
(``__getitem__``) + set_value op. Static python indices (ints/slices/ellipsis/
None) are baked into the jit cache key; Tensor indices are passed as dynamic
args (XLA gather). Boolean-mask indexing concretizes the mask via np.nonzero
(eager-only — dynamic output shape) and then rides the integer gather op, so
the selected values stay on the autograd tape.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor

_SLICE = "s"
_INT = "i"
_NONE = "n"
_ELL = "e"
_TENSOR = "t"
_ARRAY = "a"


def _mask_to_int_indices(mask_data, x_shape, axis):
    """Concretize a boolean mask into integer index arrays (numpy semantics:
    x[mask] == x[np.nonzero(mask)]).  The mask itself carries no gradient, so
    concretizing it is lossless; routing the result through the integer
    gather op keeps the *selected values* on the autograd tape (the reference
    propagates grads through bool-mask selection — eager_method.cc)."""
    if isinstance(mask_data, jax.core.Tracer):
        raise TypeError(
            "boolean-mask indexing has a data-dependent output shape and "
            "cannot be traced under to_static/jit; use paddle.where or "
            "masked_select outside the traced region")
    mask = np.asarray(mask_data)
    if x_shape is not None:
        covered = tuple(x_shape[axis:axis + mask.ndim])
        if mask.shape != covered:
            raise IndexError(
                f"boolean mask shape {mask.shape} does not match indexed "
                f"axes {covered} of array shape {tuple(x_shape)}")
    nz = np.nonzero(mask)
    return [jnp.asarray(ix) for ix in nz]


def _bool_mask(it):
    """The mask data if `it` is a non-scalar boolean mask, else None."""
    if isinstance(it, Tensor) and it.dtype == np.dtype("bool"):
        data = it._data
    elif (isinstance(it, (jax.Array, np.ndarray))
            and np.dtype(it.dtype) == np.dtype("bool")):
        data = it
    elif (isinstance(it, (list, tuple))
            and np.asarray(it).dtype == np.dtype("bool")):
        data = np.asarray(it)
    else:
        return None
    if np.ndim(data) == 0:
        return None  # 0-d mask behaves like a scalar bool (new axis)
    return data


def _axes_consumed(idx):
    """How many axes of x each index element consumes (None/newaxis: 0,
    bool mask of rank k: k, everything else: 1); Ellipsis resolved later."""
    counts = []
    for it in idx:
        if it is None:
            counts.append(0)
        elif it is Ellipsis:
            counts.append(-1)  # placeholder
        else:
            m = _bool_mask(it)
            counts.append(np.ndim(m) if m is not None else 1)
    return counts


def _canon(idx, x_shape=None):
    """Split an index expr into a hashable static spec + dynamic tensor list."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    # scalar bool index (adds a size-0/1 axis) → numpy eager path
    for it in idx:
        if isinstance(it, (bool, np.bool_)):
            return None, None
        if (isinstance(it, (Tensor, jax.Array, np.ndarray))
                and np.dtype(getattr(it, "dtype", None) or "V0")
                == np.dtype("bool") and np.ndim(
                    it._data if isinstance(it, Tensor) else it) == 0):
            return None, None
    counts = _axes_consumed(idx)
    if -1 in counts and x_shape is not None:
        rest = sum(c for c in counts if c > 0)
        counts[counts.index(-1)] = max(len(x_shape) - rest, 0)
    axis = 0
    spec = []
    tensors = []
    for it, consumed in zip(idx, counts):
        mask = None if isinstance(it, (bool, np.bool_)) else _bool_mask(it)
        if mask is not None:
            data = mask._data if isinstance(mask, Tensor) else mask
            for ix in _mask_to_int_indices(data, x_shape, axis):
                spec.append((_TENSOR, len(tensors)))
                tensors.append(Tensor._wrap(ix))
            axis += consumed
            continue
        if isinstance(it, Tensor):
            spec.append((_TENSOR, len(tensors)))
            tensors.append(it)
        elif isinstance(it, (jax.Array, np.ndarray)):
            spec.append((_TENSOR, len(tensors)))
            tensors.append(Tensor._wrap(jnp.asarray(it)))
        elif isinstance(it, slice):
            spec.append((_SLICE, it.start, it.stop, it.step))
        elif it is None:
            spec.append((_NONE,))
        elif it is Ellipsis:
            spec.append((_ELL,))
        elif isinstance(it, (int, np.integer)):
            spec.append((_INT, int(it)))
        elif isinstance(it, (list, tuple)):
            spec.append((_TENSOR, len(tensors)))
            tensors.append(Tensor._wrap(jnp.asarray(np.asarray(it))))
        else:
            raise TypeError(f"unsupported index type {type(it)}")
        axis += max(consumed, 0)
    return tuple(spec), tensors


def _rebuild(spec, arrs):
    out = []
    for s in spec:
        tag = s[0]
        if tag == _SLICE:
            out.append(slice(s[1], s[2], s[3]))
        elif tag == _INT:
            out.append(s[1])
        elif tag == _NONE:
            out.append(None)
        elif tag == _ELL:
            out.append(Ellipsis)
        elif tag == _TENSOR:
            out.append(arrs[s[1]])
    return tuple(out)


@op("getitem")
def _getitem(x, *index_arrays, spec=()):
    return x[_rebuild(spec, index_arrays)]


@op("set_value")
def _setitem(x, value, *index_arrays, spec=()):
    return x.at[_rebuild(spec, index_arrays)].set(value)


def getitem(x, idx):
    spec, tensors = _canon(idx, x_shape=tuple(x._data.shape))
    if spec is None:
        # boolean mask: eager-only dynamic shape
        mask = idx if not isinstance(idx, tuple) else idx
        data = np.asarray(x._data)[_np_index(mask)]
        return Tensor._wrap(jnp.asarray(data))
    return _getitem(x, *tensors, spec=spec)


def setitem_(x, idx, value):
    spec, tensors = _canon(idx, x_shape=tuple(x._data.shape))
    if not isinstance(value, Tensor):
        value = Tensor._wrap(jnp.asarray(np.asarray(value), x._data.dtype))
    if value.dtype != x.dtype:
        value = Tensor._wrap(jnp.asarray(value._data, x._data.dtype))
    if spec is None:
        arr = np.asarray(x._data)
        arr[_np_index(idx)] = np.asarray(value._data)
        x._rebind(jnp.asarray(arr))
        return x
    out = _setitem(x, value, *tensors, spec=spec)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient and x.stop_gradient
    return x


def _np_index(idx):
    def conv(it):
        if isinstance(it, Tensor):
            return np.asarray(it._data)
        return it

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)
