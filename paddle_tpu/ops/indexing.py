"""__getitem__/__setitem__ support.

Reference: the pybind slice machinery in paddle/fluid/pybind/eager_method.cc
(``__getitem__``) + set_value op. Static python indices (ints/slices/ellipsis/
None) are baked into the jit cache key; Tensor indices are passed as dynamic
args (XLA gather). Boolean-mask indexing is eager-only (dynamic output shape).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor

_SLICE = "s"
_INT = "i"
_NONE = "n"
_ELL = "e"
_TENSOR = "t"
_ARRAY = "a"


def _canon(idx):
    """Split an index expr into a hashable static spec + dynamic tensor list."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    spec = []
    tensors = []
    for it in idx:
        if isinstance(it, Tensor):
            if it.dtype == np.dtype("bool"):
                return None, None  # boolean mask → eager path
            spec.append((_TENSOR, len(tensors)))
            tensors.append(it)
        elif isinstance(it, (jax.Array, np.ndarray)):
            if np.dtype(it.dtype) == np.dtype("bool"):
                return None, None
            spec.append((_TENSOR, len(tensors)))
            tensors.append(Tensor._wrap(jnp.asarray(it)))
        elif isinstance(it, slice):
            spec.append((_SLICE, it.start, it.stop, it.step))
        elif it is None:
            spec.append((_NONE,))
        elif it is Ellipsis:
            spec.append((_ELL,))
        elif isinstance(it, (int, np.integer)):
            spec.append((_INT, int(it)))
        elif isinstance(it, (list, tuple)):
            arr = np.asarray(it)
            if arr.dtype == np.dtype("bool"):
                return None, None
            spec.append((_TENSOR, len(tensors)))
            tensors.append(Tensor._wrap(jnp.asarray(arr)))
        elif isinstance(it, (bool, np.bool_)):
            return None, None
        else:
            raise TypeError(f"unsupported index type {type(it)}")
    return tuple(spec), tensors


def _rebuild(spec, arrs):
    out = []
    for s in spec:
        tag = s[0]
        if tag == _SLICE:
            out.append(slice(s[1], s[2], s[3]))
        elif tag == _INT:
            out.append(s[1])
        elif tag == _NONE:
            out.append(None)
        elif tag == _ELL:
            out.append(Ellipsis)
        elif tag == _TENSOR:
            out.append(arrs[s[1]])
    return tuple(out)


@op("getitem")
def _getitem(x, *index_arrays, spec=()):
    return x[_rebuild(spec, index_arrays)]


@op("set_value")
def _setitem(x, value, *index_arrays, spec=()):
    return x.at[_rebuild(spec, index_arrays)].set(value)


def getitem(x, idx):
    spec, tensors = _canon(idx)
    if spec is None:
        # boolean mask: eager-only dynamic shape
        mask = idx if not isinstance(idx, tuple) else idx
        data = np.asarray(x._data)[_np_index(mask)]
        return Tensor._wrap(jnp.asarray(data))
    return _getitem(x, *tensors, spec=spec)


def setitem_(x, idx, value):
    spec, tensors = _canon(idx)
    if not isinstance(value, Tensor):
        value = Tensor._wrap(jnp.asarray(np.asarray(value), x._data.dtype))
    if value.dtype != x.dtype:
        value = Tensor._wrap(jnp.asarray(value._data, x._data.dtype))
    if spec is None:
        arr = np.asarray(x._data)
        arr[_np_index(idx)] = np.asarray(value._data)
        x._rebind(jnp.asarray(arr))
        return x
    out = _setitem(x, value, *tensors, spec=spec)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient and x.stop_gradient
    return x


def _np_index(idx):
    def conv(it):
        if isinstance(it, Tensor):
            return np.asarray(it._data)
        return it

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)
