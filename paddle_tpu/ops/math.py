"""Elementwise math + reduction ops.

Reference: python/paddle/tensor/math.py dispatching to PHI kernels
(paddle/phi/kernels/elementwise_*.h, reduce_*.h). Here each op is a pure JAX
function; XLA fuses chains of these into single kernels, which replaces the
reference's hand-fused CUDA elementwise kernels.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.dispatch import op
from ..core.tensor import Tensor

__all__ = []


def _export(name):
    __all__.append(name)


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---------------- binary elementwise ----------------

def _binary(name, fn):
    fwd = op(name)(fn)

    def public(x, y, name=None):
        return fwd(x, y)

    public.__name__ = name
    _export(name)
    return public


add = _binary("add", lambda x, y: jnp.add(x, y))
subtract = _binary("subtract", lambda x, y: jnp.subtract(x, y))
multiply = _binary("multiply", lambda x, y: jnp.multiply(x, y))
divide = _binary("divide", lambda x, y: jnp.true_divide(x, y))
floor_divide = _binary("floor_divide", lambda x, y: jnp.floor_divide(x, y))
remainder = _binary("remainder", lambda x, y: jnp.remainder(x, y))
mod = remainder
floor_mod = remainder
pow_ = _binary("elementwise_pow", lambda x, y: jnp.power(x, y))
elementwise_pow = pow_
maximum = _binary("maximum", lambda x, y: jnp.maximum(x, y))
minimum = _binary("minimum", lambda x, y: jnp.minimum(x, y))
fmax = _binary("fmax", lambda x, y: jnp.fmax(x, y))
fmin = _binary("fmin", lambda x, y: jnp.fmin(x, y))
atan2 = _binary("atan2", lambda x, y: jnp.arctan2(x, y))
hypot = _binary("hypot", lambda x, y: jnp.hypot(x, y))
logaddexp = _binary("logaddexp", lambda x, y: jnp.logaddexp(x, y))
nextafter = _binary("nextafter", lambda x, y: jnp.nextafter(x, y))
copysign = _binary("copysign", lambda x, y: jnp.copysign(x, y))
heaviside = _binary("heaviside", lambda x, y: jnp.heaviside(x, y))
gcd = _binary("gcd", lambda x, y: jnp.gcd(x, y))
lcm = _binary("lcm", lambda x, y: jnp.lcm(x, y))
inner = _binary("inner", lambda x, y: jnp.inner(x, y))
outer = _binary("outer", lambda x, y: jnp.outer(x.ravel(), y.ravel()))
kron = _binary("kron", lambda x, y: jnp.kron(x, y))
_export("mod"), _export("floor_mod")


def pow(x, y, name=None):  # noqa: A001
    return pow_(x, y)


_export("pow")


# ---------------- unary elementwise ----------------

def _unary(name, fn, differentiable=True):
    fwd = op(name, differentiable=differentiable)(fn)

    def public(x, name=None):
        return fwd(x)

    public.__name__ = name
    _export(name)
    return public


exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)  # noqa: A001
sign = _unary("sign", jnp.sign)
neg = _unary("neg", jnp.negative)
reciprocal = _unary("reciprocal", jnp.reciprocal)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)  # noqa: A001
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
isnan = _unary("isnan", jnp.isnan, differentiable=False)
isinf = _unary("isinf", jnp.isinf, differentiable=False)
isfinite = _unary("isfinite", jnp.isfinite, differentiable=False)
i0 = _unary("i0", jnp.i0)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)


@op("scale")
def _scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    if bias_after_scale:
        out = x * jnp.asarray(scale, x.dtype) + jnp.asarray(bias, x.dtype)
    else:
        out = (x + jnp.asarray(bias, x.dtype)) * jnp.asarray(scale, x.dtype)
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    if isinstance(scale, Tensor):
        scale = scale.item()
    return _scale(x, scale=float(scale), bias=float(bias),
                  bias_after_scale=bool(bias_after_scale))


_export("scale")


@op("clip")
def _clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def clip(x, min=None, max=None, name=None):
    def val(v):
        return float(v.item()) if isinstance(v, Tensor) else (None if v is None else float(v))
    return _clip(x, min=val(min), max=val(max))


_export("clip")


@op("nan_to_num")
def _nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _nan_to_num(x, nan=float(nan),
                       posinf=None if posinf is None else float(posinf),
                       neginf=None if neginf is None else float(neginf))


_export("nan_to_num")


@op("lerp")
def _lerp(x, y, weight):
    return x + weight * (y - x)


def lerp(x, y, weight, name=None):
    return _lerp(x, y, weight)


_export("lerp")


@op("stanh")
def _stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _stanh(x, scale_a=float(scale_a), scale_b=float(scale_b))


_export("stanh")


# ---------------- matmul family ----------------

@op("matmul")
def _matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        axes = list(range(x.ndim))
        axes[-1], axes[-2] = axes[-2], axes[-1]
        x = jnp.transpose(x, axes)
    if transpose_y:
        axes = list(range(y.ndim))
        axes[-1], axes[-2] = axes[-2], axes[-1]
        y = jnp.transpose(y, axes)
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _matmul(x, y, transpose_x=bool(transpose_x), transpose_y=bool(transpose_y))


_export("matmul")


@op("dot")
def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


def dot(x, y, name=None):
    return _dot(x, y)


_export("dot")


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


_export("mm"), _export("bmm")


@op("addmm")
def _addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _addmm(input, x, y, beta=float(beta), alpha=float(alpha))


_export("addmm")


@op("multiply_acc")  # t-accumulate helper used by optimizers
def _axpy(x, y, alpha=1.0):
    return x + alpha * y


# ---------------- reductions ----------------

@op("sum")
def _sum(x, axis=None, dtype=None, keepdim=False):
    if dtype is None and jnp.issubdtype(x.dtype, jnp.bool_):
        dtype = jnp.int32
    return jnp.sum(x, axis=axis, dtype=dtype, keepdims=keepdim)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    return _sum(x, axis=_axis(axis), dtype=dtypes.convert_dtype(dtype),
                keepdim=bool(keepdim))


_export("sum")


@op("nansum")
def _nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=axis, dtype=dtype, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _nansum(x, axis=_axis(axis), dtype=dtypes.convert_dtype(dtype),
                   keepdim=bool(keepdim))


_export("nansum")


@op("mean")
def _mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return _mean(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("mean")


@op("nanmean")
def _nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False, name=None):
    return _nanmean(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("nanmean")


@op("prod")
def _prod(x, axis=None, dtype=None, keepdim=False):
    return jnp.prod(x, axis=axis, dtype=dtype, keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _prod(x, axis=_axis(axis), dtype=dtypes.convert_dtype(dtype),
                 keepdim=bool(keepdim))


_export("prod")


@op("max")
def _max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _max(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("max")


@op("min")
def _min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _min(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("min")


@op("amax")
def _amax(x, axis=None, keepdim=False):
    return jnp.amax(x, axis=axis, keepdims=keepdim)


def amax(x, axis=None, keepdim=False, name=None):
    return _amax(x, axis=_axis(axis), keepdim=bool(keepdim))


@op("amin")
def _amin(x, axis=None, keepdim=False):
    return jnp.amin(x, axis=axis, keepdims=keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return _amin(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("amax"), _export("amin")


@op("std")
def _std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _std(x, axis=_axis(axis), unbiased=bool(unbiased), keepdim=bool(keepdim))


_export("std")


@op("var")
def _var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _var(x, axis=_axis(axis), unbiased=bool(unbiased), keepdim=bool(keepdim))


_export("var")


@op("median")
def _median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return _median(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("median")


@op("quantile")
def _quantile(x, q=0.5, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return _quantile(x, q=q if isinstance(q, (list, tuple)) else float(q),
                     axis=_axis(axis), keepdim=bool(keepdim))


_export("quantile")


@op("logsumexp")
def _logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _logsumexp(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("logsumexp")


@op("all", differentiable=False)
def _all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _all(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("all")


@op("any", differentiable=False)
def _any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _any(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("any")


@op("count_nonzero", differentiable=False)
def _count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim).astype(jnp.int32)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _count_nonzero(x, axis=_axis(axis), keepdim=bool(keepdim))


_export("count_nonzero")


# ---------------- scans ----------------

@op("cumsum")
def _cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.ravel()
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumsum(x, axis=None, dtype=None, name=None):
    return _cumsum(x, axis=None if axis is None else int(axis),
                   dtype=dtypes.convert_dtype(dtype))


_export("cumsum")


@op("cumprod")
def _cumprod(x, dim=None, dtype=None):
    if dim is None:
        x = x.ravel()
        dim = 0
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def cumprod(x, dim=None, dtype=None, name=None):
    return _cumprod(x, dim=None if dim is None else int(dim),
                    dtype=dtypes.convert_dtype(dtype))


_export("cumprod")


@op("cummax", differentiable=False)
def _cummax(x, axis=-1):
    return jax.lax.associative_scan(jnp.maximum, x, axis=axis)


def cummax(x, axis=None, dtype="int64", name=None):
    ax = -1 if axis is None else int(axis)
    vals = _cummax(x if axis is not None else x.flatten(), axis=ax)
    return vals


_export("cummax")


@op("trace")
def _trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _trace(x, offset=int(offset), axis1=int(axis1), axis2=int(axis2))


_export("trace")


@op("diff")
def _diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return _diff(x, n=int(n), axis=int(axis))


_export("diff")


def increment(x, value=1.0, name=None):
    x._rebind((x + float(value))._data)
    return x


_export("increment")
