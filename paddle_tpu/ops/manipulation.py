"""Shape/layout manipulation ops.

Reference: python/paddle/tensor/manipulation.py + PHI kernels
(reshape_kernel.h, concat_kernel.h, gather_kernel.h ...). All shape arguments
are static (XLA requirement); Tensor-valued shapes are concretized eagerly.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.dispatch import op
from ..core.tensor import Tensor

__all__ = [
    "cast", "reshape", "reshape_", "transpose", "concat", "stack", "split",
    "chunk", "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "flatten",
    "expand", "broadcast_to", "expand_as", "tile", "flip", "rot90", "roll",
    "gather", "gather_nd", "scatter", "scatter_nd_add", "index_select",
    "index_sample", "index_add", "index_put", "where", "masked_select",
    "masked_fill", "topk", "sort", "argsort", "argmax", "argmin", "unbind",
    "unique", "unique_consecutive", "nonzero", "pad", "take_along_axis",
    "put_along_axis", "tensordot", "moveaxis", "swapaxes", "as_real",
    "as_complex", "view", "view_as", "atleast_1d", "atleast_2d", "atleast_3d",
    "repeat_interleave", "broadcast_tensors", "crop", "tolist", "unstack",
    "strided_slice", "slice", "searchsorted", "bucketize", "numel", "shard_index",
    "diagonal", "kthvalue", "mode", "flatten_", "tensor_split", "hsplit",
    "vsplit", "dsplit", "as_strided", "histogram", "bincount",
]


def _ints(v):
    if isinstance(v, Tensor):
        v = v.tolist()
    if isinstance(v, (int, np.integer)):
        return int(v)
    return tuple(int(x.item() if isinstance(x, Tensor) else x) for x in v)


@op("cast")
def _cast(x, dtype=None):
    return x.astype(dtype)


def cast(x, dtype, name=None):
    return _cast(x, dtype=dtypes.convert_dtype(dtype))


@op("reshape")
def _reshape(x, shape=()):
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    return _reshape(x, shape=_ints(shape))


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


@op("transpose")
def _transpose(x, perm=None):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    return _transpose(x, perm=_ints(perm))


def t(x, name=None):
    if x.ndim < 2:
        return x
    return transpose(x, [1, 0])


def moveaxis(x, source, destination, name=None):
    fwd = op_moveaxis(x, source=_ints(source), destination=_ints(destination))
    return fwd


@op("moveaxis")
def op_moveaxis(x, source=0, destination=0):
    return jnp.moveaxis(x, source, destination)


@op("swapaxes")
def _swapaxes(x, axis1=0, axis2=1):
    return jnp.swapaxes(x, axis1, axis2)


def swapaxes(x, axis1, axis2, name=None):
    return _swapaxes(x, axis1=int(axis1), axis2=int(axis2))


swapdims = swapaxes


@op("concat_n")
def _concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _concat(*x, axis=int(axis))


@op("stack_n")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return _stack(*x, axis=int(axis))


def row_stack(x, name=None):
    """Alias of vstack (the reference aliases them; stacking 1-D rows and
    concatenating >=2-D along axis 0)."""
    from .extras import vstack

    return vstack(x)


@op("split")
def _split(x, indices=(), axis=0):
    return tuple(jnp.split(x, indices, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        n = num_or_sections
        assert dim % n == 0, f"dim {dim} not divisible by {n}"
        indices = tuple(dim // n * i for i in range(1, n))
    else:
        secs = [int(s.item() if isinstance(s, Tensor) else s) for s in num_or_sections]
        n_neg = [i for i, s in enumerate(secs) if s < 0]
        if n_neg:
            secs[n_neg[0]] = dim - sum(s for s in secs if s >= 0)
        indices = tuple(np.cumsum(secs[:-1]).tolist())
    return list(_split(x, indices=indices, axis=axis))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    axis = int(axis)
    if isinstance(num_or_indices, int):
        arrs = np.array_split(np.arange(x.shape[axis]), num_or_indices)
        indices = tuple(int(a[0]) for a in arrs[1:])
    else:
        indices = tuple(int(i) for i in num_or_indices)
    return list(_split(x, indices=indices, axis=axis))


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def unstack(x, axis=0, num=None, name=None):
    n = x.shape[axis] if num is None else num
    outs = split(x, n, axis)
    return [squeeze(o, axis) for o in outs]


@op("squeeze")
def _squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    axes = axis if isinstance(axis, tuple) else (axis,)
    axes = tuple(a for a in axes if x.shape[a] == 1)
    return jnp.squeeze(x, axes) if axes else x


def squeeze(x, axis=None, name=None):
    return _squeeze(x, axis=None if axis is None else _ints(axis))


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


@op("unsqueeze")
def _unsqueeze(x, axis=0):
    axes = axis if isinstance(axis, tuple) else (axis,)
    return jnp.expand_dims(x, axes)


def unsqueeze(x, axis, name=None):
    return _unsqueeze(x, axis=_ints(axis))


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


@op("flatten")
def _flatten(x, start_axis=0, stop_axis=-1):
    shape = list(x.shape)
    n = len(shape)
    s = start_axis % n if n else 0
    e = stop_axis % n if n else 0
    new = shape[:s] + [int(np.prod(shape[s : e + 1] or [1]))] + shape[e + 1 :]
    return jnp.reshape(x, new)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    if x.ndim == 0:
        return reshape(x, [1])
    return _flatten(x, start_axis=int(start_axis), stop_axis=int(stop_axis))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


@op("broadcast_to")
def _broadcast_to(x, shape=()):
    return jnp.broadcast_to(x, shape)


def broadcast_to(x, shape, name=None):
    shape = list(_ints(shape))
    # paddle expand semantics: -1 means keep dim
    xs = list(x.shape)
    offset = len(shape) - len(xs)
    for i, s in enumerate(shape):
        if s == -1 and i >= offset:
            shape[i] = xs[i - offset]
    return _broadcast_to(x, shape=tuple(shape))


def expand(x, shape, name=None):
    return broadcast_to(x, shape)


def expand_as(x, y, name=None):
    return broadcast_to(x, y.shape)


def broadcast_tensors(inputs, name=None):
    shape = np.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [broadcast_to(t, shape) for t in inputs]


@op("tile")
def _tile(x, repeat_times=()):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    return _tile(x, repeat_times=_ints(repeat_times))


@op("flip")
def _flip(x, axis=()):
    return jnp.flip(x, axis)


def flip(x, axis, name=None):
    return _flip(x, axis=_ints(axis))


@op("rot90")
def _rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k, axes)


def rot90(x, k=1, axes=(0, 1), name=None):
    return _rot90(x, k=int(k), axes=_ints(axes))


@op("roll")
def _roll(x, shifts=0, axis=None):
    return jnp.roll(x, shifts, axis)


def roll(x, shifts, axis=None, name=None):
    return _roll(x, shifts=_ints(shifts), axis=None if axis is None else _ints(axis))


# ---------------- gather/scatter ----------------

@op("gather")
def _gather(x, index, axis=0):
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _gather(x, index, axis=int(axis))


@op("gather_nd")
def _gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index, name=None):
    return _gather_nd(x, index)


@op("scatter")
def _scatter(x, index, updates, overwrite=True):
    if index.ndim == 2:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    # paddle overwrite=False: zero out target rows then accumulate
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return _scatter(x, index, updates, overwrite=bool(overwrite))


@op("scatter_nd_add")
def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    return _scatter_nd_add(x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    return _scatter_nd_add(zeros(shape, updates.dtype), index, updates)


@op("index_select")
def _index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0, name=None):
    return _index_select(x, index, axis=int(axis))


@op("index_sample")
def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_sample(x, index, name=None):
    return _index_sample(x, index)


@op("index_add")
def _index_add(x, index, value, axis=0):
    moved = jnp.moveaxis(x, axis, 0)
    vmoved = jnp.moveaxis(value, axis, 0)
    out = moved.at[index].add(vmoved)
    return jnp.moveaxis(out, 0, axis)


def index_add(x, index, axis, value, name=None):
    return _index_add(x, index, value, axis=int(axis))


@op("index_put")
def _index_put(x, value, *indices, accumulate=False):
    if accumulate:
        return x.at[tuple(indices)].add(value)
    return x.at[tuple(indices)].set(value)


def index_put(x, indices, value, accumulate=False, name=None):
    return _index_put(x, value, *indices, accumulate=bool(accumulate))


@op("take_along_axis")
def _take_along_axis(x, index, axis=0):
    return jnp.take_along_axis(x, index, axis=axis)


def take_along_axis(x, indices, axis, broadcast=True, name=None):
    return _take_along_axis(x, indices, axis=int(axis))


@op("put_along_axis")
def _put_along_axis(x, index, value, axis=0, reduce="assign"):
    if reduce in ("add", "sum"):
        return jnp.put_along_axis(x, index, value, axis=axis, inplace=False, mode="add")
    return jnp.put_along_axis(x, index, value, axis=axis, inplace=False)


def put_along_axis(x, indices, values, axis, reduce="assign", name=None, **kw):
    if not isinstance(values, (Tensor, jax.Array, np.ndarray)):
        values = jnp.asarray(values, x.dtype)
    return _put_along_axis(x, indices, values, axis=int(axis), reduce=reduce)


@op("where")
def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return _where(condition, x, y)


@op("masked_fill")
def _masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        return _masked_fill(x, mask, value)
    return _masked_fill(x, mask, jnp.asarray(value))


# ---- dynamic-shape ops: eager-only (not traceable under jit; the reference's
# LoD/dynamic ops have no XLA analog — callers inside @to_static should use
# masking instead). ----

def masked_select(x, mask, name=None):
    data = np.asarray(x._data)[np.asarray(mask._data)]
    return Tensor._wrap(jnp.asarray(data))


def nonzero(x, as_tuple=False):
    idx = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor._wrap(jnp.asarray(i)) for i in idx)
    return Tensor._wrap(jnp.asarray(np.stack(idx, axis=-1)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    res = np.unique(np.asarray(x._data), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not isinstance(res, tuple):
        return Tensor._wrap(jnp.asarray(res))
    return tuple(Tensor._wrap(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.ravel()
        keep = np.concatenate([[True], arr[1:] != arr[:-1]])
    else:
        diff = (np.diff(arr, axis=axis) != 0).any(
            axis=tuple(i for i in range(arr.ndim) if i != axis)
        )
        keep = np.concatenate([[True], diff])
    out = arr[keep] if axis is None else np.compress(keep, arr, axis=axis)
    outs = [Tensor._wrap(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor._wrap(jnp.asarray(inv)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.concatenate([idx, [len(keep)]]))
        outs.append(Tensor._wrap(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


# ---------------- sort/search ----------------

@op("topk")
def _topk(x, k=1, axis=-1, largest=True):
    if largest:
        vals, idx = jax.lax.top_k(jnp.moveaxis(x, axis, -1), k)
    else:
        vals, idx = jax.lax.top_k(-jnp.moveaxis(x, axis, -1), k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis).astype(jnp.int32)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    return tuple(_topk(x, k=int(k), axis=int(axis if axis is not None else -1),
                       largest=bool(largest)))


@op("sort_op")
def _sort(x, axis=-1, descending=False):
    s = jnp.sort(x, axis=axis)
    return jnp.flip(s, axis=axis) if descending else s


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return _sort(x, axis=int(axis), descending=bool(descending))


@op("argsort", differentiable=False)
def _argsort(x, axis=-1, descending=False, stable=False):
    idx = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return idx.astype(jnp.int32)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    return _argsort(x, axis=int(axis), descending=bool(descending), stable=bool(stable))


@op("argmax", differentiable=False)
def _argmax(x, axis=None, keepdim=False, dtype=None):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(dtype or jnp.int32)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _argmax(x, axis=None if axis is None else int(axis), keepdim=bool(keepdim),
                   dtype=jnp.int32)


@op("argmin", differentiable=False)
def _argmin(x, axis=None, keepdim=False, dtype=None):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(dtype or jnp.int32)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _argmin(x, axis=None if axis is None else int(axis), keepdim=bool(keepdim),
                   dtype=jnp.int32)


@op("kthvalue")
def _kthvalue(x, k=1, axis=-1, keepdim=False):
    s = jnp.sort(x, axis=axis)
    idx = jnp.argsort(x, axis=axis).astype(jnp.int32)
    vals = jnp.take(s, k - 1, axis=axis)
    inds = jnp.take(idx, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        inds = jnp.expand_dims(inds, axis)
    return vals, inds


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    return tuple(_kthvalue(x, k=int(k), axis=int(axis), keepdim=bool(keepdim)))


@op("mode")
def _mode(x, axis=-1, keepdim=False):
    moved = jnp.moveaxis(x, axis, -1)
    n = moved.shape[-1]
    # O(n^2) count of equal values — fine for the modest n this op sees; keeps
    # the whole thing one fused XLA kernel with static shapes.
    eq = moved[..., :, None] == moved[..., None, :]
    counts = jnp.sum(eq, axis=-1)
    # bias ties toward the largest value (paddle/torch semantics)
    score = counts.astype(jnp.float32) * n + jnp.argsort(
        jnp.argsort(moved, axis=-1), axis=-1
    ).astype(jnp.float32) / n
    best = jnp.argmax(score, axis=-1)
    vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
    eqv = moved == vals[..., None]
    idxs = jnp.where(eqv, jnp.arange(n, dtype=jnp.int32), -1)
    inds = jnp.max(idxs, axis=-1)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        inds = jnp.expand_dims(inds, axis)
    return vals, inds


def mode(x, axis=-1, keepdim=False, name=None):
    return tuple(_mode(x, axis=int(axis), keepdim=bool(keepdim)))


@op("searchsorted", differentiable=False)
def _searchsorted(sorted_sequence, values, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        return jnp.searchsorted(sorted_sequence, values, side=side).astype(jnp.int32)
    vs = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))
    flat_s = sorted_sequence.reshape(-1, sorted_sequence.shape[-1])
    flat_v = values.reshape(-1, values.shape[-1])
    return vs(flat_s, flat_v).reshape(values.shape).astype(jnp.int32)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    return _searchsorted(sorted_sequence, values, right=bool(right))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return _searchsorted(sorted_sequence, x, right=bool(right))


@op("histogram", differentiable=False)
def _histogram(x, bins=100, min=0, max=0):
    lo, hi = (min, max) if (min != 0 or max != 0) else (None, None)
    if lo is None:
        lo = jnp.min(x)
        hi = jnp.max(x)
    h, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return h.astype(jnp.int32)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    return _histogram(input, bins=int(bins), min=min, max=max)


@op("bincount", differentiable=False)
def _bincount(x, minlength=0):
    return jnp.bincount(x, minlength=minlength, length=None)


def bincount(x, weights=None, minlength=0, name=None):
    arr = np.asarray(x._data)
    w = None if weights is None else np.asarray(weights._data)
    return Tensor._wrap(jnp.asarray(np.bincount(arr, weights=w, minlength=minlength)))


# ---------------- pad / slice ----------------

@op("pad_nd")
def _pad(x, paddings=(), mode="constant", value=0.0):
    pads = list(paddings)
    if mode == "constant":
        return jnp.pad(x, pads, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, pads, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format=None, name=None):  # noqa: A002
    """paddle.nn.functional.pad-style; `pad` is [before,after] per trailing dims
    (paddle order: last dim first) or full nd spec."""
    pad = _ints(pad)
    n = x.ndim
    if len(pad) == 2 * n:
        pairs = tuple((pad[2 * i], pad[2 * i + 1]) for i in range(n))
    else:
        k = len(pad) // 2
        pairs = tuple((0, 0) for _ in range(n - k)) + tuple(
            (pad[2 * i], pad[2 * i + 1]) for i in range(k)
        )
    return _pad(x, paddings=pairs, mode=mode, value=float(value))


@op("slice_op")
def _slice(x, axes=(), starts=(), ends=(), strides=None):
    idx = [slice(None)] * x.ndim
    for i, ax in enumerate(axes):
        st = strides[i] if strides else 1
        idx[ax] = slice(starts[i], ends[i], st)
    return x[tuple(idx)]


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    return _slice(x, axes=_ints(axes), starts=_ints(starts), ends=_ints(ends))


def strided_slice(x, axes, starts, ends, strides, name=None):
    return _slice(x, axes=_ints(axes), starts=_ints(starts), ends=_ints(ends),
                  strides=_ints(strides))


def crop(x, shape=None, offsets=None, name=None):
    shape = _ints(shape)
    offsets = _ints(offsets) if offsets is not None else tuple(0 for _ in shape)
    axes = tuple(range(x.ndim))
    xs = x.shape
    shape = tuple(xs[i] if s == -1 else s for i, s in enumerate(shape))
    return _slice(x, axes=axes, starts=offsets,
                  ends=tuple(o + s for o, s in zip(offsets, shape)))


@op("diagonal")
def _diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset, axis1, axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _diagonal(x, offset=int(offset), axis1=int(axis1), axis2=int(axis2))


@op("repeat_interleave")
def _repeat_interleave(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        arr = np.asarray(x._data)
        out = np.repeat(arr, np.asarray(repeats._data), axis=axis)
        return Tensor._wrap(jnp.asarray(out))
    return _repeat_interleave(x, repeats=int(repeats),
                              axis=None if axis is None else int(axis))


@op("tensordot")
def _tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(_ints(a)) if isinstance(a, (list, tuple)) else int(a)
                     for a in axes)
    else:
        axes = int(axes)
    return _tensordot(x, y, axes=axes)


@op("as_complex")
def _as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_complex(x, name=None):
    return _as_complex(x)


@op("as_real")
def _as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_real(x, name=None):
    return _as_real(x)


def atleast_1d(*inputs, name=None):
    outs = [reshape(x, [1]) if x.ndim == 0 else x for x in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_2d(*inputs, name=None):
    outs = []
    for x in inputs:
        while x.ndim < 2:
            x = unsqueeze(x, 0)
        outs.append(x)
    return outs if len(outs) > 1 else outs[0]


def atleast_3d(*inputs, name=None):
    outs = []
    for x in inputs:
        while x.ndim < 3:
            x = unsqueeze(x, -1) if x.ndim >= 2 else unsqueeze(x, 0)
        outs.append(x)
    return outs if len(outs) > 1 else outs[0]


def unbind(x, axis=0, name=None):
    return unstack(x, axis)


def as_strided(x, shape, stride, offset=0, name=None):
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(x._data).ravel()[offset:],
        shape=shape,
        strides=[s * x.dtype.itemsize for s in stride],
    )
    return Tensor._wrap(jnp.asarray(arr.copy()))


def tolist(x):
    return x.tolist()


def numel(x, name=None):
    from .creation import to_tensor

    return to_tensor(x.size, dtype="int64")


@op("shard_index", differentiable=False)
def _shard_index(x, index_num=0, nshards=1, shard_id=0, ignore_value=-1):
    size = (index_num + nshards - 1) // nshards
    owner = x // size
    local = x % size
    return jnp.where(owner == shard_id, local, ignore_value)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _shard_index(input, index_num=int(index_num), nshards=int(nshards),
                        shard_id=int(shard_id), ignore_value=int(ignore_value))
