"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import op
from ..core.tensor import Tensor

__all__ = []


def _cmp(name, fn):
    fwd = op(name, differentiable=False)(fn)

    def public(x, y, name=None):
        return fwd(x, y)

    public.__name__ = name
    __all__.append(name)
    return public


equal = _cmp("equal", lambda x, y: jnp.equal(x, y))
not_equal = _cmp("not_equal", lambda x, y: jnp.not_equal(x, y))
greater_than = _cmp("greater_than", lambda x, y: jnp.greater(x, y))
greater_equal = _cmp("greater_equal", lambda x, y: jnp.greater_equal(x, y))
less_than = _cmp("less_than", lambda x, y: jnp.less(x, y))
less_equal = _cmp("less_equal", lambda x, y: jnp.less_equal(x, y))
logical_and = _cmp("logical_and", lambda x, y: jnp.logical_and(x, y))
logical_or = _cmp("logical_or", lambda x, y: jnp.logical_or(x, y))
logical_xor = _cmp("logical_xor", lambda x, y: jnp.logical_xor(x, y))
bitwise_and = _cmp("bitwise_and", lambda x, y: jnp.bitwise_and(x, y))
bitwise_or = _cmp("bitwise_or", lambda x, y: jnp.bitwise_or(x, y))
bitwise_xor = _cmp("bitwise_xor", lambda x, y: jnp.bitwise_xor(x, y))
bitwise_left_shift = _cmp("bitwise_left_shift", lambda x, y: jnp.left_shift(x, y))
bitwise_right_shift = _cmp("bitwise_right_shift", lambda x, y: jnp.right_shift(x, y))


@op("logical_not", differentiable=False)
def _logical_not(x):
    return jnp.logical_not(x)


def logical_not(x, out=None, name=None):
    return _logical_not(x)


@op("bitwise_not", differentiable=False)
def _bitwise_not(x):
    return jnp.bitwise_not(x)


def bitwise_not(x, out=None, name=None):
    return _bitwise_not(x)


@op("isclose", differentiable=False)
def _isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    return _isclose(x, y, rtol=float(rtol), atol=float(atol), equal_nan=bool(equal_nan))


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    from .math import all as all_op

    return all_op(isclose(x, y, rtol, atol, equal_nan))


def equal_all(x, y, name=None):
    from .math import all as all_op

    if tuple(x.shape) != tuple(y.shape):
        from .creation import to_tensor

        return to_tensor(False)
    return all_op(equal(x, y))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    from .creation import to_tensor

    return to_tensor(x.size == 0)


def in_dynamic_mode():
    return True


__all__ += [
    "logical_not", "bitwise_not", "isclose", "allclose", "equal_all",
    "is_tensor", "is_empty",
]
