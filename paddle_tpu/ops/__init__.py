"""Functional op namespace.

Analog of the reference's generated ``paddle._C_ops`` + ``python/paddle/tensor``
package: every op is a pure-JAX function registered with the dispatch layer
(core/dispatch.py). Importing this package also installs the op-method surface
onto ``Tensor`` (the reference generates those bindings from YAML via
python_c_gen.py; here installation is introspective).
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..core import dtype as _dtypes

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from . import (  # noqa: F401
    creation, extras, indexing, linalg, logic, manipulation, math,
    sparse_grad,
)
from .manipulation import row_stack, t  # noqa: F401

from .math import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, remainder, matmul, pow,
    neg, abs, maximum, minimum, sum, mean, max, min, all, any,
)
from .manipulation import cast, reshape, transpose, concat, where  # noqa: F401


# ---------------------------------------------------------------------------
# Tensor method + operator installation
# ---------------------------------------------------------------------------

_METHOD_SOURCES = [creation, math, manipulation, logic, linalg, extras]

# names whose first parameter is NOT a tensor (skip when installing methods)
_NON_METHODS = {
    "to_tensor", "zeros", "ones", "full", "empty", "arange", "linspace",
    "logspace", "eye", "meshgrid", "rand", "randn", "randint", "uniform",
    "normal", "randperm", "standard_normal", "gaussian", "einsum", "multi_dot",
    "broadcast_tensors", "one_hot", "scatter_nd", "is_tensor",
    "hstack", "vstack", "dstack", "column_stack", "multiplex",
    "broadcast_shape", "tril_indices", "triu_indices", "add_n", "binomial",
    "finfo", "iinfo", "complex", "polar",
}


def _install():
    import types

    for mod in _METHOD_SOURCES:
        names = getattr(mod, "__all__", None) or [
            n for n in dir(mod) if not n.startswith("_")
        ]
        for name in names:
            fn = getattr(mod, name, None)
            if not isinstance(fn, types.FunctionType):
                continue
            if name in _NON_METHODS:
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)

    def _swap(fn):
        def rev(self, other):
            return fn(other, self)

        return rev

    def _coerce(fn):
        def method(self, other):
            return fn(self, other)

        return method

    Tensor.__add__ = _coerce(add)
    Tensor.__radd__ = _swap(add)
    Tensor.__sub__ = _coerce(subtract)
    Tensor.__rsub__ = _swap(subtract)
    Tensor.__mul__ = _coerce(multiply)
    Tensor.__rmul__ = _swap(multiply)
    Tensor.__truediv__ = _coerce(divide)
    Tensor.__rtruediv__ = _swap(divide)
    Tensor.__floordiv__ = _coerce(floor_divide)
    Tensor.__rfloordiv__ = _swap(floor_divide)
    Tensor.__mod__ = _coerce(remainder)
    Tensor.__rmod__ = _swap(remainder)
    Tensor.__pow__ = _coerce(pow)
    Tensor.__rpow__ = _swap(pow)
    Tensor.__matmul__ = _coerce(matmul)
    Tensor.__rmatmul__ = _swap(matmul)
    Tensor.__neg__ = lambda self: neg(self)
    Tensor.__abs__ = lambda self: abs(self)
    Tensor.__invert__ = lambda self: logic.logical_not(self)
    Tensor.__eq__ = _coerce(logic.equal)
    Tensor.__ne__ = _coerce(logic.not_equal)
    Tensor.__lt__ = _coerce(logic.less_than)
    Tensor.__le__ = _coerce(logic.less_equal)
    Tensor.__gt__ = _coerce(logic.greater_than)
    Tensor.__ge__ = _coerce(logic.greater_equal)
    Tensor.__and__ = _coerce(logic.logical_and)
    Tensor.__or__ = _coerce(logic.logical_or)
    Tensor.__xor__ = _coerce(logic.logical_xor)
    Tensor.__hash__ = lambda self: id(self)

    # common in-place helpers (rebind semantics; see tensor.py docstring)
    def _inplace(name, fn):
        def method(self, *args, **kwargs):
            out = fn(self, *args, **kwargs)
            self._data, self._node, self._out_idx = out._data, out._node, out._out_idx
            self.stop_gradient = out.stop_gradient and self.stop_gradient
            return self

        method.__name__ = name
        setattr(Tensor, name, method)

    # the reference's full Tensor inplace surface (python/paddle/__init__.py
    # `*_` names); each rebinds to the out-of-place op result — under XLA
    # every op is functional, so "inplace" is an aliasing contract, not a
    # memory optimization (donation handles that under jit)
    _INPLACE_BASES = [
        "add", "subtract", "multiply", "divide", "scale", "clip", "exp",
        "sqrt", "rsqrt", "floor", "ceil", "round", "abs", "tanh", "acos",
        "asin", "atan", "cos", "sin", "sinh", "cosh", "tan", "erf", "expm1",
        "digamma", "lgamma", "log", "log2", "log10", "log1p", "neg",
        "square", "trunc", "frac", "i0", "gcd", "lcm", "hypot", "ldexp",
        "nan_to_num", "logit", "pow", "remainder", "mod", "floor_mod",
        "floor_divide", "cumsum", "cumprod", "equal", "not_equal",
        "greater_equal", "greater_than", "less_equal", "less_than",
        "logical_and", "logical_or", "logical_not", "logical_xor",
        "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor",
        "masked_fill", "masked_scatter", "index_add", "index_fill",
        "index_put", "renorm", "scatter", "tril", "triu", "t", "transpose",
        "cast", "where", "lerp", "reciprocal", "sigmoid", "addmm",
        "put_along_axis", "sign", "atan2", "divide", "flatten", "squeeze",
        "unsqueeze", "reshape", "polygamma", "multigammaln", "atanh",
        "acosh", "asinh", "erfinv",
    ]
    _sources = [math, manipulation, logic, linalg, extras, creation]
    for base in _INPLACE_BASES:
        fn = None
        for mod in _sources:
            fn = getattr(mod, base, None)
            if fn is not None:
                break
        if fn is not None:
            _inplace(base + "_", fn)

    # inplace random re-initializers (reference tensor/random.py normal_,
    # cauchy_, geometric_ mutate in place from a fresh sample)
    def _inplace_random(name, sample):
        def method(self, *args, **kwargs):
            import jax.numpy as jnp

            self._data = sample(self, *args, **kwargs).astype(self._data.dtype)
            return self

        method.__name__ = name
        setattr(Tensor, name, method)

    def _normal_sample(self, mean=0.0, std=1.0, shape=None, name=None):
        import jax

        from ..core import rng

        return mean + std * jax.random.normal(rng.next_key(),
                                              self._data.shape)

    def _cauchy_sample(self, loc=0, scale=1, name=None):
        import jax

        from ..core import rng

        return loc + scale * jax.random.cauchy(rng.next_key(),
                                               self._data.shape)

    def _geometric_sample(self, probs, name=None):
        import jax

        from ..core import rng

        return jax.random.geometric(rng.next_key(), probs,
                                    self._data.shape).astype("float32")

    def _exponential_sample(self, lam=1.0, name=None):
        import jax

        from ..core import rng

        return jax.random.exponential(rng.next_key(), self._data.shape) / lam

    def _uniform_sample(self, min=-1.0, max=1.0, seed=0, name=None):
        import jax

        from ..core import rng

        return jax.random.uniform(rng.next_key(), self._data.shape,
                                  minval=min, maxval=max)

    def _bernoulli_sample(self, p=0.5, name=None):
        import jax

        from ..core import rng

        return jax.random.bernoulli(rng.next_key(), p, self._data.shape)

    def _log_normal_sample(self, mean=1.0, std=2.0, name=None):
        import jax
        import jax.numpy as jnp

        from ..core import rng

        return jnp.exp(mean + std * jax.random.normal(rng.next_key(),
                                                      self._data.shape))

    _inplace_random("normal_", _normal_sample)
    _inplace_random("cauchy_", _cauchy_sample)
    _inplace_random("geometric_", _geometric_sample)
    _inplace_random("bernoulli_", _bernoulli_sample)
    _inplace_random("log_normal_", _log_normal_sample)
    if not hasattr(Tensor, "exponential_"):
        _inplace_random("exponential_", _exponential_sample)
    if not hasattr(Tensor, "uniform_"):
        _inplace_random("uniform_", _uniform_sample)

    def zero_(self):
        import jax.numpy as jnp

        self._data = jnp.zeros_like(self._data)
        return self

    def fill_(self, value):
        import jax.numpy as jnp

        self._data = jnp.full_like(self._data, value)
        return self

    Tensor.zero_ = zero_
    Tensor.fill_ = fill_
    Tensor.item = Tensor.item  # keep

    # paddle-style aliases
    Tensor.mm = math.mm
    Tensor.t = manipulation.t
    Tensor.dim = lambda self: self.ndim
    Tensor.rank = lambda self: self.ndim
    Tensor.cpu = Tensor.cpu

    # the reference monkey-patches every tensor_method_func name onto
    # Tensor, including module-level factories — bind the stragglers so the
    # method surface audits complete (python/paddle/tensor/__init__.py)
    Tensor.inverse = extras.inverse
    Tensor.top_p_sampling = extras.top_p_sampling
    Tensor.multiplex = lambda self, index: extras.multiplex([self], index)
    Tensor.polar = staticmethod(extras.polar)
    Tensor.add_n = staticmethod(extras.add_n)
    Tensor.broadcast_shape = staticmethod(extras.broadcast_shape)
    Tensor.scatter_nd = staticmethod(extras.scatter_nd)
    Tensor.pca_lowrank = linalg.pca_lowrank
    Tensor.householder_product = linalg.householder_product
    Tensor.lu_unpack = linalg.lu_unpack
    Tensor.multi_dot = staticmethod(linalg.multi_dot)
    Tensor.broadcast_tensors = staticmethod(manipulation.broadcast_tensors)
    Tensor.is_tensor = staticmethod(
        lambda x: isinstance(x, Tensor))

    def _tensor_stft(self, *args, **kwargs):
        from .. import signal

        return signal.stft(self, *args, **kwargs)

    def _tensor_istft(self, *args, **kwargs):
        from .. import signal

        return signal.istft(self, *args, **kwargs)

    Tensor.stft = _tensor_stft
    Tensor.istft = _tensor_istft

    def _create_parameter(shape, dtype="float32", **kwargs):
        import paddle_tpu

        return paddle_tpu.create_parameter(shape, dtype, **kwargs)

    def _create_tensor(dtype="float32", name=None, persistable=False):
        import numpy as _np

        from ..core import dtype as _dt

        t = Tensor(_np.zeros((0,), _dt.convert_dtype(dtype)))
        t.persistable = persistable
        return t

    Tensor.create_parameter = staticmethod(_create_parameter)
    Tensor.create_tensor = staticmethod(_create_tensor)


_install()
del _install


def _export_inplace_toplevel():
    """Reference exposes every Tensor inplace method as paddle.<name>_ too
    (python/paddle/__init__.py __all__)."""
    import sys

    mod = sys.modules[__name__]
    for name in dir(Tensor):
        if (name.endswith("_") and not name.startswith("_")
                and not hasattr(mod, name)):
            def _make(n):
                def f(x, *args, **kwargs):
                    return getattr(x, n)(*args, **kwargs)

                f.__name__ = n
                return f

            setattr(mod, name, _make(name))


_export_inplace_toplevel()
del _export_inplace_toplevel
