"""Functional op namespace.

Analog of the reference's generated ``paddle._C_ops`` + ``python/paddle/tensor``
package: every op is a pure-JAX function registered with the dispatch layer
(core/dispatch.py). Importing this package also installs the op-method surface
onto ``Tensor`` (the reference generates those bindings from YAML via
python_c_gen.py; here installation is introspective).
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..core import dtype as _dtypes

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from . import creation, indexing, linalg, logic, manipulation, math  # noqa: F401

from .math import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, remainder, matmul, pow,
    neg, abs, maximum, minimum, sum, mean, max, min, all, any,
)
from .manipulation import cast, reshape, transpose, concat, where  # noqa: F401


# ---------------------------------------------------------------------------
# Tensor method + operator installation
# ---------------------------------------------------------------------------

_METHOD_SOURCES = [creation, math, manipulation, logic, linalg]

# names whose first parameter is NOT a tensor (skip when installing methods)
_NON_METHODS = {
    "to_tensor", "zeros", "ones", "full", "empty", "arange", "linspace",
    "logspace", "eye", "meshgrid", "rand", "randn", "randint", "uniform",
    "normal", "randperm", "standard_normal", "gaussian", "einsum", "multi_dot",
    "broadcast_tensors", "one_hot", "scatter_nd", "is_tensor",
}


def _install():
    import types

    for mod in _METHOD_SOURCES:
        names = getattr(mod, "__all__", None) or [
            n for n in dir(mod) if not n.startswith("_")
        ]
        for name in names:
            fn = getattr(mod, name, None)
            if not isinstance(fn, types.FunctionType):
                continue
            if name in _NON_METHODS:
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)

    def _swap(fn):
        def rev(self, other):
            return fn(other, self)

        return rev

    def _coerce(fn):
        def method(self, other):
            return fn(self, other)

        return method

    Tensor.__add__ = _coerce(add)
    Tensor.__radd__ = _swap(add)
    Tensor.__sub__ = _coerce(subtract)
    Tensor.__rsub__ = _swap(subtract)
    Tensor.__mul__ = _coerce(multiply)
    Tensor.__rmul__ = _swap(multiply)
    Tensor.__truediv__ = _coerce(divide)
    Tensor.__rtruediv__ = _swap(divide)
    Tensor.__floordiv__ = _coerce(floor_divide)
    Tensor.__rfloordiv__ = _swap(floor_divide)
    Tensor.__mod__ = _coerce(remainder)
    Tensor.__rmod__ = _swap(remainder)
    Tensor.__pow__ = _coerce(pow)
    Tensor.__rpow__ = _swap(pow)
    Tensor.__matmul__ = _coerce(matmul)
    Tensor.__rmatmul__ = _swap(matmul)
    Tensor.__neg__ = lambda self: neg(self)
    Tensor.__abs__ = lambda self: abs(self)
    Tensor.__invert__ = lambda self: logic.logical_not(self)
    Tensor.__eq__ = _coerce(logic.equal)
    Tensor.__ne__ = _coerce(logic.not_equal)
    Tensor.__lt__ = _coerce(logic.less_than)
    Tensor.__le__ = _coerce(logic.less_equal)
    Tensor.__gt__ = _coerce(logic.greater_than)
    Tensor.__ge__ = _coerce(logic.greater_equal)
    Tensor.__and__ = _coerce(logic.logical_and)
    Tensor.__or__ = _coerce(logic.logical_or)
    Tensor.__xor__ = _coerce(logic.logical_xor)
    Tensor.__hash__ = lambda self: id(self)

    # common in-place helpers (rebind semantics; see tensor.py docstring)
    def _inplace(name, fn):
        def method(self, *args, **kwargs):
            out = fn(self, *args, **kwargs)
            self._data, self._node, self._out_idx = out._data, out._node, out._out_idx
            self.stop_gradient = out.stop_gradient and self.stop_gradient
            return self

        method.__name__ = name
        setattr(Tensor, name, method)

    _inplace("add_", add)
    _inplace("subtract_", subtract)
    _inplace("multiply_", multiply)
    _inplace("divide_", divide)
    _inplace("scale_", math.scale)
    _inplace("clip_", math.clip)
    _inplace("exp_", math.exp)
    _inplace("sqrt_", math.sqrt)
    _inplace("rsqrt_", math.rsqrt)
    _inplace("floor_", math.floor)
    _inplace("ceil_", math.ceil)
    _inplace("round_", math.round)
    _inplace("abs_", math.abs)
    _inplace("tanh_", math.tanh)

    def zero_(self):
        import jax.numpy as jnp

        self._data = jnp.zeros_like(self._data)
        return self

    def fill_(self, value):
        import jax.numpy as jnp

        self._data = jnp.full_like(self._data, value)
        return self

    Tensor.zero_ = zero_
    Tensor.fill_ = fill_
    Tensor.item = Tensor.item  # keep

    # paddle-style aliases
    Tensor.mm = math.mm
    Tensor.t = manipulation.t
    Tensor.dim = lambda self: self.ndim
    Tensor.rank = lambda self: self.ndim
    Tensor.cpu = Tensor.cpu


_install()
del _install
