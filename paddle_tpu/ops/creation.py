"""Tensor creation ops (reference: python/paddle/tensor/creation.py,
kernels paddle/phi/kernels/full_kernel.h etc.)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import rng
from ..core.dispatch import op
from ..core.tensor import Tensor, to_tensor  # noqa: F401

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye", "tril",
    "triu", "diag", "diagflat", "meshgrid", "assign", "clone", "one_hot",
    "rand", "randn", "randint", "randint_like", "uniform", "normal", "randperm",
    "standard_normal", "bernoulli", "multinomial", "gaussian",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    if d is None:
        d = default or dtypes.get_default_dtype()
    return d


@op("full")
def _full(shape=(), fill_value=0, dtype=None):
    return jnp.full(shape, fill_value, dtype)


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return _full(shape=_shape(shape), fill_value=fill_value, dtype=_dt(dtype))


def zeros(shape, dtype=None, name=None):
    return full(shape, 0, dtype)


def ones(shape, dtype=None, name=None):
    return full(shape, 1, dtype)


def empty(shape, dtype=None, name=None):
    return full(shape, 0, dtype)


@op("full_like")
def _full_like(x, fill_value=0, dtype=None):
    return jnp.full_like(x, fill_value, dtype=dtype)


def full_like(x, fill_value, dtype=None, name=None):
    return _full_like(x, fill_value=fill_value, dtype=dtypes.convert_dtype(dtype))


def zeros_like(x, dtype=None, name=None):
    return full_like(x, 0, dtype)


def ones_like(x, dtype=None, name=None):
    return full_like(x, 1, dtype)


def empty_like(x, dtype=None, name=None):
    return full_like(x, 0, dtype)


@op("arange")
def _arange(start=0, end=None, step=1, dtype=None):
    return jnp.arange(start, end, step, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if dtype is None:
        dtype = (
            dtypes.int64
            if all(isinstance(v, (int, type(None))) for v in (start, end, step))
            else dtypes.get_default_dtype()
        )
        if dtype == dtypes.int64:
            dtype = dtypes.int32  # TPU-friendly default (see core/dtype.py)
    return _arange(start=start, end=end, step=step, dtype=dtypes.convert_dtype(dtype))


@op("linspace")
def _linspace(start=0.0, stop=1.0, num=100, dtype=None):
    return jnp.linspace(start, stop, num, dtype=dtype)


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return _linspace(start=val(start), stop=val(stop), num=int(val(num)),
                     dtype=_dt(dtype))


@op("logspace")
def _logspace(start=0.0, stop=1.0, num=100, base=10.0, dtype=None):
    return jnp.logspace(start, stop, num, base=base, dtype=dtype)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return _logspace(start=val(start), stop=val(stop), num=int(val(num)),
                     base=float(val(base)), dtype=_dt(dtype))


@op("eye")
def _eye(num_rows=0, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=dtype)


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return _eye(num_rows=int(num_rows),
                num_columns=None if num_columns is None else int(num_columns),
                dtype=_dt(dtype))


@op("tril")
def _tril(x, diagonal=0):
    return jnp.tril(x, diagonal)


def tril(x, diagonal=0, name=None):
    return _tril(x, diagonal=int(diagonal))


@op("triu")
def _triu(x, diagonal=0):
    return jnp.triu(x, diagonal)


def triu(x, diagonal=0, name=None):
    return _triu(x, diagonal=int(diagonal))


@op("diag")
def _diag(x, offset=0):
    return jnp.diag(x, offset)


def diag(x, offset=0, padding_value=0, name=None):
    if padding_value != 0 and x.ndim == 1:
        d = _diag(x, offset=int(offset))
        import paddle_tpu.ops as ops

        n = d.shape[0]
        mask = eye(n, dtype="bool")
        if offset:
            mask = to_tensor(np.eye(n, k=offset, dtype=bool))
        return ops.where(mask, d, full_like(d, padding_value))
    return _diag(x, offset=int(offset))


def diagflat(x, offset=0, name=None):
    import paddle_tpu.ops as ops

    return _diag(ops.flatten(x), offset=int(offset))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    outs = jnp.meshgrid(*arrays, indexing="ij")
    return [Tensor._wrap(o) for o in outs]


@op("assign")
def _assign(x):
    return x + 0 if jnp.issubdtype(x.dtype, jnp.number) else jnp.array(x)


def assign(x, output=None):
    if not isinstance(x, Tensor):
        x = to_tensor(np.asarray(x))
    out = _assign(x)
    if output is not None:
        output._rebind(out._data)
        return output
    return out


def clone(x, name=None):
    return assign(x)


@op("one_hot")
def _one_hot(x, num_classes=-1, dtype=None):
    return jnp.asarray(
        jnp.arange(num_classes, dtype=jnp.int32) == x[..., None],
        dtype or jnp.float32,
    )


def one_hot(x, num_classes, name=None):
    return _one_hot(x, num_classes=int(num_classes))


# ---- random creation (phi::Generator analog: core/rng.py) ----

@op("random_uniform")
def _uniform(key, shape=(), dtype=None, min=0.0, max=1.0):
    import jax

    return jax.random.uniform(key, shape, dtype or jnp.float32, min, max)


@op("random_normal")
def _normal(key, shape=(), dtype=None, mean=0.0, std=1.0):
    import jax

    return jax.random.normal(key, shape, dtype or jnp.float32) * std + mean


@op("random_randint")
def _randint(key, shape=(), low=0, high=1, dtype=None):
    import jax

    return jax.random.randint(key, shape, low, high, dtype or jnp.int32)


@op("random_permutation", differentiable=False)
def _randperm(key, n=0, dtype=None):
    import jax

    return jax.random.permutation(key, n).astype(dtype or jnp.int32)


@op("random_bernoulli", differentiable=False)
def _bernoulli(x, key):
    import jax

    return jax.random.bernoulli(key, x).astype(x.dtype)


@op("random_categorical", differentiable=False)
def _categorical(logits, key, num_samples=1, replacement=False):
    import jax

    return jax.random.categorical(key, logits, axis=-1,
                                  shape=(*logits.shape[:-1], num_samples))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    return _uniform(rng.next_key(), shape=_shape(shape), dtype=_dt(dtype),
                    min=float(min), max=float(max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        import paddle_tpu.ops as ops

        m = mean if isinstance(mean, Tensor) else None
        shp = _shape(m.shape if m is not None else std.shape)
        base = _normal(rng.next_key(), shape=shp, dtype=dtypes.get_default_dtype())
        return ops.add(ops.multiply(base, std), mean)
    return _normal(rng.next_key(), shape=_shape(shape or []),
                   dtype=dtypes.get_default_dtype(), mean=float(mean), std=float(std))


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None):
    return _normal(rng.next_key(), shape=_shape(shape), dtype=_dt(dtype),
                   mean=float(mean), std=float(std))


def randn(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, dtype)


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return _randint(rng.next_key(), shape=_shape(shape), low=int(low),
                    high=int(high), dtype=_dt(dtype, dtypes.int32))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int32", name=None):
    return _randperm(rng.next_key(), n=int(n), dtype=_dt(dtype, dtypes.int32))


def bernoulli(x, name=None):
    return _bernoulli(x, rng.next_key())


def multinomial(x, num_samples=1, replacement=False, name=None):
    import paddle_tpu.ops as ops

    logits = ops.log(x)
    return _categorical(logits, rng.next_key(), num_samples=int(num_samples),
                        replacement=bool(replacement))
