"""Linear algebra ops (reference: python/paddle/tensor/linalg.py, kernels
paddle/phi/kernels/{cholesky,qr,svd,...}_kernel.h). Decompositions lower to
XLA's native linalg on CPU/TPU."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor

__all__ = [
    "norm", "vector_norm", "matrix_norm", "cholesky", "qr", "svd", "eig",
    "eigh", "eigvals", "eigvalsh", "inv", "pinv", "det", "slogdet", "solve",
    "triangular_solve", "cholesky_solve", "lstsq", "matrix_power", "matrix_rank",
    "cond", "cov", "corrcoef", "multi_dot", "cross", "histogramdd", "lu",
    "einsum",
]


@op("p_norm")
def _norm(x, p=2.0, axis=None, keepdim=False):
    if p == "fro" or p is None:
        p = 2.0
    if p == "inf":
        p = jnp.inf
    if p == "-inf":
        p = -jnp.inf
    if axis is None:
        x = x.ravel()
        axis = 0
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    ax = axis
    if isinstance(ax, (list, tuple)):
        ax = tuple(int(a) for a in ax)
        if p is None:
            p = "fro"
        if p == "fro":
            from .math import sqrt, sum as sum_op, square

            return sqrt(sum_op(square(x), axis=ax, keepdim=keepdim))
    elif ax is not None:
        ax = int(ax)
    if p is None:
        p = 2.0
    return _norm(x, p=p if isinstance(p, str) else float(p), axis=ax,
                 keepdim=bool(keepdim))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p, axis, keepdim)


@op("matrix_norm")
def _matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return _matrix_norm(x, p=p if isinstance(p, str) else float(p),
                        axis=tuple(axis), keepdim=bool(keepdim))


def _simple(name, fn, differentiable=True, multi_out=False):
    fwd = op(name, differentiable=differentiable)(fn)

    def public(x, name=None):
        out = fwd(x)
        return tuple(out) if multi_out else out

    public.__name__ = name
    return public


cholesky_ = op("cholesky")(lambda x, upper=False: jnp.linalg.cholesky(x) if not upper
                           else jnp.swapaxes(jnp.linalg.cholesky(x), -1, -2).conj())


def cholesky(x, upper=False, name=None):
    return cholesky_(x, upper=bool(upper))


@op("qr")
def _qr(x, mode="reduced"):
    return tuple(jnp.linalg.qr(x, mode=mode))


def qr(x, mode="reduced", name=None):
    return tuple(_qr(x, mode=mode))


@op("svd")
def _svd(x, full_matrices=False):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


def svd(x, full_matrices=False, name=None):
    return tuple(_svd(x, full_matrices=bool(full_matrices)))


@op("eigh")
def _eigh(x, UPLO="L"):
    return tuple(jnp.linalg.eigh(x, UPLO=UPLO))


def eigh(x, UPLO="L", name=None):
    return tuple(_eigh(x, UPLO=UPLO))


def eig(x, name=None):
    # general eig only on CPU in XLA; run via numpy for parity
    w, v = np.linalg.eig(np.asarray(x._data))
    return Tensor._wrap(jnp.asarray(w)), Tensor._wrap(jnp.asarray(v))


def eigvals(x, name=None):
    w = np.linalg.eigvals(np.asarray(x._data))
    return Tensor._wrap(jnp.asarray(w))


@op("eigvalsh", differentiable=False)
def _eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return _eigvalsh(x, UPLO=UPLO)


inv = _simple("inv", jnp.linalg.inv)


@op("pinv")
def _pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _pinv(x, rcond=float(rcond), hermitian=bool(hermitian))


det = _simple("det", jnp.linalg.det)


@op("slogdet")
def _slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


def slogdet(x, name=None):
    return _slogdet(x)


@op("solve")
def _solve(x, y):
    return jnp.linalg.solve(x, y)


def solve(x, y, name=None):
    if y.ndim == x.ndim - 1:
        return _solve(x, y)
    return _solve(x, y)


@op("triangular_solve")
def _triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return _triangular_solve(x, y, upper=bool(upper), transpose=bool(transpose),
                             unitriangular=bool(unitriangular))


@op("cholesky_solve")
def _cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


def cholesky_solve(x, y, upper=False, name=None):
    return _cholesky_solve(x, y, upper=bool(upper))


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = np.linalg.lstsq(np.asarray(x._data), np.asarray(y._data),
                                         rcond=rcond)
    return (Tensor._wrap(jnp.asarray(sol)), Tensor._wrap(jnp.asarray(res)),
            Tensor._wrap(jnp.asarray(rank)), Tensor._wrap(jnp.asarray(sv)))


@op("matrix_power")
def _matrix_power(x, n=1):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return _matrix_power(x, n=int(n))


@op("matrix_rank", differentiable=False)
def _matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol).astype(jnp.int32)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return _matrix_rank(x, tol=None if tol is None else float(tol),
                        hermitian=bool(hermitian))


@op("cond_op", differentiable=False)
def _cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    return _cond(x, p=p if (p is None or isinstance(p, str)) else float(p))


@op("cov")
def _cov(x, rowvar=True, ddof=True):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return _cov(x, rowvar=bool(rowvar), ddof=bool(ddof))


corrcoef_ = op("corrcoef")(lambda x, rowvar=True: jnp.corrcoef(x, rowvar=rowvar))


def corrcoef(x, rowvar=True, name=None):
    return corrcoef_(x, rowvar=bool(rowvar))


@op("multi_dot")
def _multi_dot(*xs):
    return jnp.linalg.multi_dot(xs)


def multi_dot(x, name=None):
    return _multi_dot(*x)


@op("cross")
def _cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def cross(x, y, axis=9, name=None):
    if axis == 9:  # paddle default: first axis with dim 3
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return _cross(x, y, axis=int(axis))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    h, edges = np.histogramdd(np.asarray(x._data), bins=bins, range=ranges,
                              density=density,
                              weights=None if weights is None else np.asarray(weights._data))
    return Tensor._wrap(jnp.asarray(h)), [Tensor._wrap(jnp.asarray(e)) for e in edges]


@op("lu")
def _lu(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv.astype(jnp.int32) + 1  # paddle returns 1-based pivots


def lu(x, pivot=True, get_infos=False, name=None):
    l_, p = _lu(x, pivot=bool(pivot))
    if get_infos:
        from .creation import zeros

        return l_, p, zeros([], "int32")
    return l_, p


@op("einsum_op")
def _einsum(*operands, equation=""):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    """paddle.einsum (reference: python/paddle/tensor/einsum.py)."""
    return _einsum(*operands, equation=equation)


@op("householder_product")
def _householder_product(x, tau):
    """Q from Householder reflectors (reference tensor/linalg.py
    householder_product over LAPACK orgqr): accumulate I - tau v v^T."""
    m, n = x.shape[-2], x.shape[-1]

    def one(vecs, taus):
        q = jnp.eye(m, dtype=jnp.float32)
        for i in range(n):
            v = jnp.concatenate([
                jnp.zeros((i,), jnp.float32),
                jnp.ones((1,), jnp.float32),
                vecs[i + 1:, i].astype(jnp.float32)])
            q = q - taus[i] * (q @ v)[:, None] * v[None, :]
        return q

    if x.ndim == 2:
        return one(x, tau).astype(x.dtype)
    batch = x.reshape((-1,) + x.shape[-2:])
    taus = tau.reshape((-1,) + tau.shape[-1:])
    out = jax.vmap(one)(batch, taus)
    return out.reshape(x.shape[:-2] + (m, m)).astype(x.dtype)


def householder_product(x, tau, name=None):
    return _householder_product(x, tau)


@op("lu_unpack", differentiable=False)
def _lu_unpack(lu_data, pivots, unpack_ludata=True, unpack_pivots=True):
    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)
    lower = jnp.tril(lu_data[..., :, :k], -1) + jnp.eye(m, k,
                                                       dtype=lu_data.dtype)
    upper = jnp.triu(lu_data[..., :k, :])
    # pivots (1-based LAPACK swaps) -> permutation matrix
    def perm_of(piv):
        perm = jnp.arange(m)

        def body(i, p):
            j = piv[i] - 1
            pi, pj = p[i], p[j]
            return p.at[i].set(pj).at[j].set(pi)

        perm = jax.lax.fori_loop(0, piv.shape[0], body, perm)
        return jax.nn.one_hot(perm, m, dtype=lu_data.dtype).T

    if lu_data.ndim == 2:
        pmat = perm_of(pivots)
    else:
        pmat = jax.vmap(perm_of)(pivots.reshape(-1, pivots.shape[-1]))
        pmat = pmat.reshape(lu_data.shape[:-2] + (m, m))
    return pmat, lower, upper


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """(P, L, U) from paddle.linalg.lu output (reference tensor/linalg.py
    lu_unpack)."""
    return _lu_unpack(x, y, unpack_ludata=unpack_ludata,
                      unpack_pivots=unpack_pivots)


@op("matrix_exp")
def _matrix_exp(x):
    return jax.scipy.linalg.expm(x.astype(jnp.float32)).astype(x.dtype)


def matrix_exp(x, name=None):
    return _matrix_exp(x)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Dense PCA via exact SVD (reference tensor/linalg.py pca_lowrank's
    randomized algorithm trades exactness for speed on huge dense GPUs;
    at these ranks exact SVD on the MXU is cheaper)."""
    from ..core.tensor import Tensor

    d = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    m, n = d.shape[-2], d.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        d = d - jnp.mean(d, axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(d.astype(jnp.float32), full_matrices=False)
    return (Tensor(u[..., :q]), Tensor(s[..., :q]),
            Tensor(jnp.swapaxes(vt, -1, -2)[..., :q]))


__all__ += ["householder_product", "lu_unpack", "matrix_exp", "pca_lowrank"]
