"""Per-op HLO cost audit — where a compiled step's bytes and FLOPs go.

XLA's ``cost_analysis()`` reports one aggregate number per executable;
that is enough for MFU accounting (``FusedTrainStep.lowered_flops``) but
useless for *finding* the op that eats the bandwidth. PERF.md's lesson is
that the only fusions worth writing are cross-op HBM-traffic removals XLA
cannot see — so the campaign needs a per-op ledger of the OPTIMIZED HLO
(post-fusion, the program that actually runs), not guesses.

This module parses ``compiled.as_text()`` — the scheduled HLO module —
and assigns each entry-computation instruction:

- **bytes**: estimated memory traffic. Elementwise/reduce ops read their
  operands and write their result in full; ``dynamic-slice``/``gather``
  read only the addressed region (a 1M-row table behind a gather costs
  row traffic, not a table stream); ``dynamic-update-slice`` aliases its
  buffer and touches only the update region. A ``fusion`` charges its
  result plus each external operand at the granularity the fused body
  actually touches it (an operand consumed solely through slices/gathers
  counts region reads). ``while``/``call`` are costed per iteration of
  their body × a trip count recovered from the loop condition's bound
  constant — loop-carried buffers are updated in place, not streamed.
- **flops**: ``dot``/``convolution`` from their contraction shapes
  (2*MNK-style), elementwise/reduce ops one per output element, data
  movement zero; fusions/loops sum (×trip) their bodies.

These are first-order estimates for *ranking*, not for MFU — the
aggregate backend number stays authoritative and is reported alongside.
The audit is how ISSUE 6's acceptance is checked mechanically: on the
lazy-Adam path, deepfm's top-bytes table must no longer contain
vocab-sized dense scatter/update ops (``vocab_sized_ops``)."""

from __future__ import annotations

import re

__all__ = ["parse_hlo_costs", "audit", "format_table", "vocab_sized_ops"]

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "s4": 1, "u4": 1,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"          # instruction name
    r"((?:\([^=]*?\))|(?:[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?))\s+"
    r"([\w\-]+)\(")                                  # opcode
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\([^)]*\)\s*->.*\{\s*$")
_CALLS_RE = re.compile(r"(?:calls|to_apply|body)=%?([\w.\-]+)")
_COND_RE = re.compile(r"condition=%?([\w.\-]+)")
_DIM_LABELS_RE = re.compile(r"dim_labels=([\w]+)_([\w]+)->([\w]+)")

_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "power", "maximum", "minimum",
    "abs", "negate", "sign", "exponential", "exponential-minus-one", "log",
    "log-plus-one", "sqrt", "rsqrt", "cbrt", "tanh", "logistic", "sine",
    "cosine", "tan", "atan2", "floor", "ceil", "round-nearest-afz",
    "round-nearest-even", "remainder", "and", "or", "xor", "not", "compare",
    "select", "clamp", "convert", "is-finite", "shift-left",
    "shift-right-arithmetic", "shift-right-logical", "popcnt", "clz",
    "stochastic-convert", "erf",
}
# no traffic of their own inside a costed scope (reads are charged to the
# consuming op; metadata/layout ops are free)
_FREE = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "broadcast", "reshape", "transpose", "iota",
    "after-all", "partition-id", "replica-id", "optimization-barrier",
    "copy-start", "copy-done",
}
_CONTROL = {"while", "call", "conditional"}


def _shape_tokens(text):
    """All (dtype, dims tuple) shape tokens in an HLO text fragment."""
    out = []
    for m in _SHAPE_RE.finditer(text):
        dt = m.group(1)
        if dt not in _DTYPE_BYTES:
            continue
        dims = tuple(int(d) for d in m.group(2).split(",") if d)
        out.append((dt, dims))
    return out


def _nbytes(tok):
    dt, dims = tok
    n = _DTYPE_BYTES[dt]
    for d in dims:
        n *= d
    return n


def _numel(dims):
    n = 1
    for d in dims:
        n *= d
    return n


def _strip_tail(line):
    """Drop metadata=/backend_config= tails whose strings can hold
    anything shape-regex-like."""
    return re.split(r",\s*(?:metadata|backend_config|sharding)=", line)[0]


class _Instr:
    __slots__ = ("name", "opcode", "result_txt", "results", "operands",
                 "line")

    def __init__(self, name, opcode, result_txt, line, operand_txt):
        self.name = name
        self.opcode = opcode
        self.result_txt = result_txt
        self.results = _shape_tokens(result_txt)
        # operand_txt starts right after the opcode's opening paren, so
        # operands[0] is the first REAL operand (never the result token)
        self.operands = _shape_tokens(_strip_tail(operand_txt))
        self.line = line


def _parse_computations(hlo_text):
    """{computation name: (is_entry, [_Instr])}."""
    comps = {}
    cur = None
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _COMP_RE.match(line)
        if m:
            cur = []
            comps[m.group(2)] = (bool(m.group(1)), cur)
            continue
        if cur is None:
            continue
        if line == "}":
            cur = None
            continue
        mi = _INSTR_RE.match(line)
        if mi:
            cur.append(_Instr(mi.group(1), mi.group(3), mi.group(2), line,
                              line[mi.end():]))
    return comps


def _instr_flops(ins):
    """First-order FLOP estimate for one non-control instruction."""
    res_elems = sum(_numel(d) for _, d in ins.results)
    op = ins.opcode
    if op == "dot":
        m = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", ins.line)
        if ins.operands and m:
            lhs = ins.operands[0][1]
            k = 1
            for i in (int(x) for x in m.group(1).split(",") if x):
                if i < len(lhs):
                    k *= lhs[i]
            return 2.0 * res_elems * k
        return 2.0 * res_elems
    if op == "convolution":
        # contraction extent per output element = input-feature size x the
        # WINDOW footprint (the window attribute stays truthful for
        # gradient convs, where the kernel operand is a big activation and
        # prod(rhs)/out_channels would overcount by orders of magnitude)
        m = _DIM_LABELS_RE.search(ins.line)
        wm = re.search(r"window=\{size=([0-9x]+)", ins.line)
        if len(ins.operands) >= 2 and m:
            rhs_labels = m.group(2)
            rhs = ins.operands[1][1]
            i_idx = rhs_labels.find("i")
            if wm and 0 <= i_idx < len(rhs):
                k = rhs[i_idx]
                for w in wm.group(1).split("x"):
                    k *= int(w)
            else:
                k = _numel(rhs)
                o_idx = rhs_labels.find("o")
                if 0 <= o_idx < len(rhs) and rhs[o_idx]:
                    k //= rhs[o_idx]
            return 2.0 * res_elems * k
        return 2.0 * res_elems
    if op in ("reduce", "reduce-window", "all-reduce"):
        return float(_numel(ins.operands[0][1]) if ins.operands
                     else res_elems)
    if op == "scatter":
        upd = ins.operands[2][1] if len(ins.operands) >= 3 else ()
        return float(_numel(upd)) if upd else float(res_elems)
    if op in ("map", "sort"):
        return float(res_elems)
    if op in _ELEMENTWISE:
        return float(res_elems)
    return 0.0


def _instr_bytes(ins):
    """Region-granular traffic estimate for one non-control, non-fusion
    instruction inside a costed scope."""
    op = ins.opcode
    res = sum(_nbytes(t) for t in ins.results)
    if op in _FREE:
        return 0.0
    if op in ("dynamic-slice", "gather"):
        # reads only the addressed region (== result), never the full
        # operand — THE distinction that keeps an embedding gather from
        # being billed a full table stream
        idx = sum(_nbytes(t) for t in ins.operands[1:])
        return float(2 * res + idx)
    if op == "dynamic-update-slice":
        # aliases operand 0; touches the update region (read+write) only
        upd = _nbytes(ins.operands[1]) if len(ins.operands) > 1 else res
        idx = sum(_nbytes(t) for t in ins.operands[2:])
        return float(2 * upd + idx)
    if op == "scatter":
        upd = _nbytes(ins.operands[2]) if len(ins.operands) >= 3 else res
        idx = _nbytes(ins.operands[1]) if len(ins.operands) >= 2 else 0
        # updates read + target regions read-modify-write
        return float(3 * upd + idx)
    if op in ("slice", "pad", "reverse", "concatenate", "copy"):
        return float(res + sum(_nbytes(t) for t in ins.operands))
    # default: full operand reads + result write
    return float(res + sum(_nbytes(t) for t in ins.operands))


def _body_cost(comp_name, comps, seen=frozenset()):
    """(bytes, flops) of one execution of a computation's body, with
    nested control flow expanded."""
    if comp_name in seen or comp_name not in comps:
        return 0.0, 0.0
    seen = seen | {comp_name}
    b = f = 0.0
    for ins in comps[comp_name][1]:
        ib, fl = _cost_one(ins, comps, seen)
        b += ib
        f += fl
    return b, f


def _trip_count(ins, comps):
    """Heuristic while-loop trip count: the largest integer bound constant
    in the loop's condition computation (the scatter/map loops this audit
    cares about compare an induction variable against a fixed bound)."""
    m = _COND_RE.search(ins.line)
    if not m or m.group(1) not in comps:
        return 1
    best = 1
    for cond_ins in comps[m.group(1)][1]:
        for c in re.finditer(r"constant\((\d+)\)", cond_ins.line):
            best = max(best, int(c.group(1)))
    return best


def _fusion_cost(ins, comps):
    """A fusion's traffic: result write + each external operand read at
    the granularity the fused body touches it (an operand consumed only
    through gathers/slices counts those regions, not its full size).
    FLOPs: the fused body's."""
    called = _CALLS_RE.findall(ins.line)
    body_b = body_f = 0.0
    touched = {}
    for cname in called:
        if cname not in comps:
            continue
        _, instrs = comps[cname]
        params = {}  # %param name -> (index, shape token)
        for i2 in instrs:
            if i2.opcode == "parameter":
                pm = re.search(r"parameter\((\d+)\)", i2.line)
                if pm and i2.results:
                    params[i2.name] = (int(pm.group(1)), i2.results[0])
        body_f += _body_cost(cname, comps)[1]
        for pname, (pidx, ptok) in params.items():
            full = _nbytes(ptok)
            region = 0
            sliced_only = True
            for i2 in instrs:
                if i2.opcode == "parameter" or \
                        not re.search(rf"%{re.escape(pname)}\b", i2.line):
                    continue
                if re.search(rf"%{re.escape(pname)}\b",
                             _strip_tail(i2.line)
                             .split("(", 1)[-1]) is None:
                    continue
                if i2.opcode in ("dynamic-slice", "gather"):
                    region += sum(_nbytes(t) for t in i2.results)
                elif i2.opcode == "dynamic-update-slice":
                    # param updated in place: update-region traffic
                    region += 2 * (_nbytes(i2.operands[1])
                                   if len(i2.operands) > 1 else full)
                else:
                    sliced_only = False
                    break
            touched[pidx] = (min(full, region) if sliced_only and region
                             else full)
    res = sum(_nbytes(t) for t in ins.results)
    if touched:
        nb = float(res + sum(touched.values()))
    else:
        nb = float(res + sum(_nbytes(t) for t in ins.operands))
    return nb, body_f


def _cost_one(ins, comps, seen=frozenset()):
    """(bytes, flops) for one instruction, expanding control flow."""
    if ins.opcode == "fusion":
        return _fusion_cost(ins, comps)
    if ins.opcode == "while":
        trip = _trip_count(ins, comps)
        b = f = 0.0
        for cname in _CALLS_RE.findall(ins.line):
            bb, bf = _body_cost(cname, comps, seen)
            b += bb
            f += bf
        return trip * b, trip * f
    if ins.opcode in ("call", "conditional"):
        b = f = 0.0
        for cname in _CALLS_RE.findall(ins.line):
            bb, bf = _body_cost(cname, comps, seen)
            b += bb
            f += bf
        return b, f
    if ins.opcode in ("reduce", "scatter", "sort", "map"):
        # their combine computations run per element; the element cost is
        # already in _instr_flops — don't double count the called comp
        return _instr_bytes(ins), _instr_flops(ins)
    return _instr_bytes(ins), _instr_flops(ins)


def _dense_shapes(ins, comps, seen=frozenset()):
    """Shape tokens this instruction STREAMS (not merely carries): its
    results for data ops; for control flow, recursively the non-aliasing
    body results (loop state updated via dynamic-update-slice is carried
    in place, never streamed)."""
    if ins.opcode in _CONTROL:
        out = []
        for cname in _CALLS_RE.findall(ins.line):
            if cname in seen or cname not in comps:
                continue
            for i2 in comps[cname][1]:
                out.extend(_dense_shapes(i2, comps, seen | {cname}))
        return out
    if ins.opcode in _FREE - {"broadcast"} or ins.opcode in (
            "dynamic-slice", "dynamic-update-slice", "gather", "slice"):
        return []
    return list(ins.results)


def parse_hlo_costs(hlo_text):
    """Per-instruction costs of the ENTRY computation of an (optimized)
    HLO module text. Returns a list of dicts:
    ``{"name", "opcode", "shape", "bytes", "flops", "op_name"}``."""
    comps = _parse_computations(hlo_text)
    entry_instrs = None
    for name, (is_entry, instrs) in comps.items():
        if is_entry:
            entry_instrs = instrs
            break
    if entry_instrs is None:
        return []
    ops = []
    for ins in entry_instrs:
        if ins.opcode in ("parameter", "constant", "tuple",
                          "get-tuple-element"):
            continue
        nb, fl = _cost_one(ins, comps)
        md = re.search(r'op_name="([^"]*)"', ins.line)
        ops.append({
            "name": ins.name,
            "opcode": ins.opcode,
            "shape": ins.result_txt.split("{")[0],
            "bytes": float(nb),
            "flops": float(fl),
            "op_name": md.group(1) if md else "",
            "_ins": ins,
        })
    return ops


def audit(compiled, top_n=None):
    """Cost report for a compiled executable (anything with ``as_text()``
    — a jax Compiled object — or a raw HLO string). Returns
    ``{"ops", "n_ops", "total_bytes", "total_flops", "backend_flops",
    "backend_bytes", "hlo_text"}`` with ``ops`` sorted by bytes
    descending (truncated to ``top_n`` when given). backend_* come from
    XLA's own aggregate ``cost_analysis`` when available — the
    authoritative totals this ranking is sanity-checked against."""
    text = compiled if isinstance(compiled, str) else compiled.as_text()
    ops = parse_hlo_costs(text)
    ops.sort(key=lambda o: (-o["bytes"], -o["flops"], o["name"]))
    report = {
        "ops": ops[:top_n] if top_n else ops,
        "n_ops": len(ops),
        "total_bytes": float(sum(o["bytes"] for o in ops)),
        "total_flops": float(sum(o["flops"] for o in ops)),
        "backend_flops": None,
        "backend_bytes": None,
        "hlo_text": text,
    }
    if not isinstance(compiled, str):
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            if hasattr(ca, "get"):
                report["backend_flops"] = ca.get("flops")
                report["backend_bytes"] = ca.get("bytes accessed")
        except Exception:
            pass
    return report


def vocab_sized_ops(report, vocab, top_n=10):
    """The acceptance probe: ops among the top-``top_n`` by bytes that
    STREAM a tensor with a dimension >= ``vocab`` (covers shard-padded
    row counts). Aliased loop state and region reads (gathers/slices into
    the table) don't count — only ops that actually produce or sweep a
    vocab-sized buffer, which is exactly what the lazy path removes."""
    comps = _parse_computations(report.get("hlo_text", ""))
    hits = []
    for o in report["ops"][:top_n]:
        ins = o.get("_ins")
        toks = (_dense_shapes(ins, comps) if ins is not None
                else _shape_tokens(o["shape"]))
        if any(any(d >= vocab for d in dims) for _, dims in toks):
            hits.append(o)
    return hits


def format_table(report, top_n=15, title=None):
    """Human-readable per-op table (bytes-ranked) with totals."""
    lines = []
    if title:
        lines.append(title)
    lines.append(f"{'op':<28} {'opcode':<18} {'shape':<26} "
                 f"{'MBytes':>10} {'MFLOPs':>10}")
    lines.append("-" * 96)
    for o in report["ops"][:top_n]:
        lines.append(
            f"{o['name'][:28]:<28} {o['opcode'][:18]:<18} "
            f"{o['shape'][:26]:<26} {o['bytes'] / 1e6:>10.3f} "
            f"{o['flops'] / 1e6:>10.3f}")
    lines.append("-" * 96)
    bf = report["backend_flops"]
    bft = f"{bf / 1e6:.3f} M" if bf else "n/a"
    lines.append(
        f"{report['n_ops']} entry ops; total "
        f"{report['total_bytes'] / 1e6:.3f} MB, "
        f"{report['total_flops'] / 1e6:.3f} MFLOPs (parsed estimate); "
        f"backend cost_analysis flops: {bft}")
    return "\n".join(lines)
