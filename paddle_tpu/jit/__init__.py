"""paddle.jit — dygraph-to-static compilation.

Reference: python/paddle/jit/ — ``to_static`` (api.py:171), ``StaticFunction``
(dy2static/program_translator.py:324), ``CacheKey`` (:192), SOT bytecode
tracer (jit/sot/), ``PartialProgramLayer`` (dy2static/partial_program.py:151)
executing via PirInterpreter.

TPU-native redesign (SURVEY.md §3.3): there is no AST rewriting, no bytecode
hook, no ProgramDesc and no interpreter. The dygraph op layer is already
pure-JAX underneath, so "to static" = run the Python function once with
tracer-backed Tensors inside ``jax.jit`` — the whole model becomes ONE XLA
executable (forward), and its backward is the jit of the program-level
``jax.vjp``. The CacheKey maps to the jit cache key (input shapes/dtypes +
training mode). Python control flow is evaluated at trace time exactly like
the reference's AST path converts it — data-dependent control flow should use
``paddle.where``/masking (the reference converts to cond/while ops; a
``lax.cond`` bridge can be added per-case).
"""

from __future__ import annotations

import functools
import threading
import traceback
import warnings

import numpy as np
import jax
import jax.export  # noqa: F401  (submodule not auto-imported on jax 0.4.3x)
import jax.numpy as jnp

from ..core import rng as rng_mod
from ..core import state
from ..core.engine import Edge, GradNode
from ..core.flags import flag_value, register_flag
from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer
from ..profiler.utils import RecordEvent
from ..static.input_spec import InputSpec
from . import cache as cache_mod
from .cache import (BucketSpec, CountingJit, cache_stats,  # noqa: F401
                    get_shape_buckets, reset_cache_stats, set_shape_buckets)
from . import hlo_audit  # noqa: F401

__all__ = ["to_static", "not_to_static", "save", "load", "TranslatedLayer",
           "enable_to_static", "ignore_module", "cache_stats",
           "reset_cache_stats", "set_shape_buckets", "get_shape_buckets",
           "BucketSpec", "CountingJit", "hlo_audit"]

_TO_STATIC_ENABLED = True

# SOT-style graceful degradation (reference: jit/sot eval-frame fallback,
# paddle/fluid/pybind/eval_frame.c:411): when tracing hits data-dependent
# control flow the whole function cannot express, fall back to running the
# function eagerly (per-call, uncompiled) with a one-time actionable warning.
# FLAGS_to_static_fallback=0 turns the fallback into a hard framework error
# carrying the same diagnostic.
register_flag("to_static_fallback", True,
              help="fall back to eager when to_static tracing hits "
                   "data-dependent control flow (SOT semantics)")

_TRACER_LEAK_ERRORS = (
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.ConcretizationTypeError,
)


def _user_frame(exc):
    """The deepest traceback frame in user code — i.e. not in an installed
    library (site-packages/dist-packages) and not in paddle_tpu itself.
    REPL/exec frames (``<stdin>``, ``<string>``) count as user code."""
    import paddle_tpu

    pkg_dir = paddle_tpu.__file__.rsplit("/", 1)[0]
    best = None
    for frame in traceback.extract_tb(exc.__traceback__):
        f = frame.filename
        if "site-packages/" in f or "dist-packages/" in f:
            continue
        if f.startswith(pkg_dir):
            continue
        best = frame
    return best


def _tracer_leak_message(fn_name, exc):
    frame = _user_frame(exc)
    where = (f'  File "{frame.filename}", line {frame.lineno}, in '
             f"{frame.name}\n"
             + (f"    {frame.line}\n" if frame.line else "")
             if frame is not None else "  (offending line inside a library "
             "call — see the chained JAX traceback)\n")
    return (
        f"to_static could not compile `{fn_name}`: a Python branch or loop "
        "depends on a Tensor VALUE, which is unknown while tracing (the "
        "whole function is compiled ONCE by XLA).\n"
        f"{where}"
        "Fix one of these ways:\n"
        "  1. paddle.static.nn.cond(pred, true_fn, false_fn) — compiles "
        "BOTH branches, differentiable.\n"
        "  2. paddle.static.nn.while_loop(cond_fn, body_fn, loop_vars) — "
        "data-dependent trip count.\n"
        "  3. paddle.where(mask, a, b) — elementwise select, usually "
        "fastest on TPU.\n"
        "  4. mark the whole function @paddle.jit.not_to_static BEFORE "
        "to_static wraps it, to always run it eagerly.\n"
        f"(original: {type(exc).__name__})")


def enable_to_static(flag: bool):
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(flag)


def ignore_module(modules):
    pass


def not_to_static(fn=None):
    if fn is None:
        return not_to_static
    fn._not_to_static = True
    return fn


class _CacheEntry:
    __slots__ = ("fwd", "bwd", "out_tree", "n_params", "params", "buffers")

    def __init__(self, fwd, bwd, out_tree, params, buffers):
        self.fwd = fwd
        self.bwd = bwd
        self.out_tree = out_tree
        self.params = params
        self.buffers = buffers


class StaticFunction:
    """Compiled wrapper over a dygraph function/Layer method.

    Reference parity: program_cache-like behavior via per-shape cache;
    ``concrete_program``/``rollback`` style helpers exposed minimally.
    """

    def __init__(self, function, input_spec=None, instance=None,
                 shape_buckets=None, bucket_args=None, **kwargs):
        self._dygraph_function = function
        self._input_spec = input_spec
        self._instance = instance
        self._cache: dict = {}
        self._shape_buckets = BucketSpec.normalize(shape_buckets)
        # None = dominant-length auto rule; a set of positional indices /
        # kw names = pad exactly those inputs (the escape hatch when a
        # fixed-size field's width can coincide with a sequence length)
        self._bucket_args = (None if bucket_args is None
                             else frozenset(bucket_args))
        functools.update_wrapper(self, function)

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = StaticFunction(self._dygraph_function, self._input_spec,
                               instance=instance,
                               shape_buckets=self._shape_buckets,
                               bucket_args=self._bucket_args)
        bound._cache = self._cache
        return bound

    # ---- cache key ----
    def _key(self, layer, args, kwargs, bucket_spec=None, lengths=None,
             selected=None):
        """``bucket_spec``/``lengths``/``selected``: shape-level bucketing
        — the key is computed from the shapes the compiled executable WOULD
        see, without materializing any padding (the eager-fallback lookup
        stays allocation-free). Must mirror the bucketize selection exactly:
        dominant-length rule when ``selected`` is None, otherwise per-leaf
        pad-up inside the explicitly selected top-level inputs."""

        def spec(x, active=True):
            if isinstance(x, Tensor):
                shape = tuple(x._data.shape)
                if bucket_spec is not None and active and x.stop_gradient:
                    if selected is None:
                        shape = cache_mod.bucketed_call_shape(
                            shape, bucket_spec, lengths)
                    else:
                        shape = cache_mod.bucketed_call_shape(
                            shape, bucket_spec,
                            cache_mod.infer_call_lengths([x._data],
                                                         bucket_spec))
                return ("T", shape, str(x.dtype), x.stop_gradient)
            if isinstance(x, (np.ndarray, jax.Array)):
                return ("A", tuple(x.shape), str(x.dtype))
            if isinstance(x, (list, tuple)):
                return tuple(spec(v, active) for v in x)
            if isinstance(x, dict):
                return tuple(sorted((k, spec(v, active))
                                    for k, v in x.items()))
            return ("P", x)

        args_spec = tuple(
            spec(a, selected is None or i in selected)
            for i, a in enumerate(args))
        kwargs_spec = tuple(sorted(
            (k, spec(v, selected is None or k in selected))
            for k, v in kwargs.items()))
        training = layer.training if isinstance(layer, Layer) else None
        return (id(layer) if layer is not None else 0, training,
                state.STATE.amp_level, args_spec, kwargs_spec)

    def _collect_layer(self):
        inst = self._instance
        if isinstance(inst, Layer):
            return inst
        if isinstance(self._dygraph_function, Layer):
            return self._dygraph_function
        return None

    def _call_eager(self, *args, **kwargs):
        if self._instance is not None:
            return self._dygraph_function(self._instance, *args, **kwargs)
        return self._dygraph_function(*args, **kwargs)

    @property
    def _stats_name(self):
        # qualified name so two layers' `forward` methods don't share a
        # cache_stats row
        return getattr(self, "__qualname__", None) or self.__name__

    def _call_eager_counted(self, *args, **kwargs):
        """Eager (uncompiled) execution of a fallen-back shape key: counted
        in cache_stats and marked as a profiler span so the 10-100x
        per-call cliff is visible, not silent."""
        span = cache_mod.record_eager_fallback(self._stats_name)
        try:
            return self._call_eager(*args, **kwargs)
        finally:
            span.end()

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED:
            return self._call_eager(*args, **kwargs)
        # eager fallbacks must see the ORIGINAL inputs: padding only pays
        # inside a compiled executable, and would change user-visible shapes
        orig_args, orig_kwargs = args, kwargs
        spec = (self._shape_buckets if self._shape_buckets is not None
                else get_shape_buckets())
        selected = self._bucket_args
        lengths = (cache_mod.infer_tree_lengths((args, kwargs), spec)
                   if spec is not None and selected is None else None)
        layer = self._collect_layer()
        # key from shape-level bucketing: every length inside a bucket
        # shares one executable, and a known-eager key short-circuits
        # below WITHOUT ever materializing pad copies
        key = self._key(layer, args, kwargs, spec, lengths, selected)
        entry = self._cache.get(key)
        if entry == "eager":  # earlier fallback for this shape key
            return self._call_eager_counted(*orig_args, **orig_kwargs)
        if spec is not None:
            if selected is None:
                (args, kwargs), n_pad = cache_mod.bucketize_tree(
                    (args, kwargs), spec, lengths)
            else:
                n_pad = 0
                new_args = list(args)
                for i in range(len(new_args)):
                    if i in selected:
                        new_args[i], n = cache_mod.bucketize_tree(
                            new_args[i], spec, per_leaf=True)
                        n_pad += n
                args = tuple(new_args)
                kwargs = dict(kwargs)
                for k in list(kwargs):
                    if k in selected:
                        kwargs[k], n = cache_mod.bucketize_tree(
                            kwargs[k], spec, per_leaf=True)
                        n_pad += n
            cache_mod.record_bucket_pads(self._stats_name, n_pad)
        if entry is not None:
            cache_mod.record_hit(self._stats_name)

        # flatten dynamic (tensor) leaves out of args
        flat_args, arg_tree = jax.tree.flatten(
            (args, kwargs),
            is_leaf=lambda x: isinstance(x, Tensor))
        dyn_idx = [i for i, a in enumerate(flat_args)
                   if isinstance(a, (Tensor, jax.Array, np.ndarray))]
        dyn_arrays = [flat_args[i]._data if isinstance(flat_args[i], Tensor)
                      else jnp.asarray(flat_args[i]) for i in dyn_idx]
        arg_requires = [isinstance(flat_args[i], Tensor)
                        and not flat_args[i].stop_gradient for i in dyn_idx]

        if entry is None:
            try:
                with RecordEvent(f"jit::compile::{self.__name__}"):
                    entry = self._trace(layer, arg_tree, flat_args, dyn_idx)
                cache_mod.record_compile(
                    self._stats_name,
                    cache_mod.shape_signature(dyn_arrays))
            except _TRACER_LEAK_ERRORS as e:
                msg = _tracer_leak_message(self.__name__, e)
                if not flag_value("to_static_fallback", True):
                    raise RuntimeError(msg) from e
                warnings.warn(msg + "\nFalling back to EAGER execution for "
                              "this function (uncompiled; set "
                              "FLAGS_to_static_fallback=0 to make this an "
                              "error). Note: the function body partially "
                              "executed once during the failed trace — "
                              "non-idempotent Python side effects (appends, "
                              "counters) before the offending line ran "
                              "twice, and values stashed during the trace "
                              "are unusable tracers.", stacklevel=2)
                entry = "eager"
            self._cache[key] = entry

        if entry == "eager":
            return self._call_eager_counted(*orig_args, **orig_kwargs)

        params = entry.params
        key_arr = rng_mod.DEFAULT_GENERATOR.next_key()
        param_arrays = [p._data for p in params]
        out_flat = entry.fwd(param_arrays, dyn_arrays, key_arr)
        outs = jax.tree.unflatten(entry.out_tree, out_flat)

        requires_grad = state.grad_enabled() and (
            any(not p.stop_gradient for p in params) or any(arg_requires))
        node = None
        if requires_grad:
            edges = [Edge.from_tensor(p) for p in params]
            dyn_tensors = [flat_args[i] for i in dyn_idx]
            edges += [Edge.from_tensor(t) if isinstance(t, Tensor)
                      else Edge(stop=True) for t in dyn_tensors]
            out_avals = [(tuple(o.shape), o.dtype) for o in out_flat]

            bwd_fn = entry.bwd

            def node_bwd(primals, cts):
                p_arrays, d_arrays, k = primals
                grads_p, grads_d = bwd_fn(p_arrays, d_arrays, k, list(cts))
                return tuple(grads_p) + tuple(grads_d)

            node = GradNode(
                f"to_static_{self.__name__}", node_bwd,
                (param_arrays, dyn_arrays, key_arr), edges, out_avals, True)

        def wrap(arr, i):
            t = Tensor._wrap(arr)
            t.stop_gradient = not requires_grad
            if node is not None:
                t._node = node
                t._out_idx = i
            return t

        wrapped_flat = [wrap(a, i) for i, a in enumerate(out_flat)]
        return jax.tree.unflatten(entry.out_tree, wrapped_flat)

    # ---- tracing ----
    def _trace(self, layer, arg_tree, flat_args, dyn_idx):
        params = list()
        if layer is not None:
            seen = set()
            for _, p in layer.named_parameters():
                if id(p) not in seen:
                    seen.add(id(p))
                    params.append(p)
            buffers = [b for _, b in layer.named_buffers()]
        else:
            buffers = []
        fn = self._dygraph_function
        instance = self._instance
        rng_counter = rng_mod.DEFAULT_GENERATOR._counter

        def pure_fn(param_arrays, dyn_arrays, key):
            # pin the rng op-counter so every retrace folds in the same
            # sequence (randomness varies per call via the traced `key` arg);
            # only the int counter is touched — never rebuild keys in-trace
            gen = rng_mod.DEFAULT_GENERATOR
            saved_counter = gen._counter
            gen._counter = rng_counter
            old_param_data = [p._data for p in params]
            new_flat = list(flat_args)
            for i, arr in zip(dyn_idx, dyn_arrays):
                orig = flat_args[i]
                t = Tensor._wrap(arr)
                if isinstance(orig, Tensor):
                    t.stop_gradient = orig.stop_gradient
                new_flat[i] = t
            args2, kwargs2 = jax.tree.unflatten(arg_tree, new_flat)
            try:
                for p, arr in zip(params, param_arrays):
                    p._data = arr
                with state.trace_guard(), gen.traced_base(key):
                    if instance is not None:
                        out = fn(instance, *args2, **kwargs2)
                    else:
                        out = fn(*args2, **kwargs2)
            finally:
                for p, arr in zip(params, old_param_data):
                    p._data = arr
                gen._counter = saved_counter
            out_flat, out_tree = jax.tree.flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            arrays = [o._data if isinstance(o, Tensor) else jnp.asarray(o)
                      for o in out_flat]
            pure_fn._out_tree = out_tree
            return arrays

        fwd = jax.jit(pure_fn)

        def bwd(param_arrays, dyn_arrays, key, cts):
            _, vjp = jax.vjp(lambda ps, ds: pure_fn(ps, ds, key),
                             param_arrays, dyn_arrays)
            return vjp(cts)

        bwd_j = jax.jit(bwd)

        # trace once eagerly (abstract) to get out_tree
        dyn_arrays = [flat_args[i]._data if isinstance(flat_args[i], Tensor)
                      else jnp.asarray(flat_args[i]) for i in dyn_idx]
        jax.eval_shape(pure_fn, [p._data for p in params], dyn_arrays,
                       jax.random.key(0))
        out_tree = pure_fn._out_tree
        return _CacheEntry(fwd, bwd_j, out_tree, params, buffers)

    @property
    def concrete_program(self):
        return self._cache

    def rollback(self):
        return self._dygraph_function


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, shape_buckets=None, bucket_args=None, **kwargs):
    """Reference: python/paddle/jit/api.py:171.

    ``shape_buckets`` (extension): pad-up bucket boundaries applied to the
    inputs before the compile-cache lookup — ``[64, 128, 256]`` buckets axis
    1, ``{axis: boundaries}`` is explicit. Caps the compile count for
    variable-length streams at O(buckets); see paddle.jit.set_shape_buckets
    for the process-global form and paddle.jit.cache_stats() for telemetry.

    ``bucket_args``: which inputs to pad. Default (None) is the
    dominant-length rule — the first tensor carrying the bucketed axis
    defines the call's length, and only tensors matching it pad. Pass an
    iterable of positional indices / kw names when a fixed-size field's
    width can coincide with a sequence length (e.g. 13 dense features and
    seq_len 13), which would otherwise mis-pad that field on exactly that
    length.
    """

    def decorate(fn):
        if isinstance(fn, Layer):
            # wrap the layer's forward; calling the layer still works because
            # we return a layer-like callable
            if getattr(type(fn).forward, "_not_to_static", False):
                return fn
            sf = StaticFunction(type(fn).forward, input_spec, instance=fn,
                                shape_buckets=shape_buckets,
                                bucket_args=bucket_args)
            fn.forward = sf
            return fn
        if getattr(fn, "_not_to_static", False):
            return fn
        return StaticFunction(fn, input_spec, shape_buckets=shape_buckets,
                              bucket_args=bucket_args)

    if function is not None:
        return decorate(function)
    return decorate


# --------------------------------------------------------------------------
# jit.save / jit.load — serialized compiled programs via jax.export
# (replaces the reference's ProgramDesc+params format,
#  python/paddle/jit/translated_layer.py)
# --------------------------------------------------------------------------

def save(layer, path, input_spec=None, **configs):
    import os
    import pickle

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, StaticFunction):
        fn = layer
        layer_obj = fn._collect_layer()
    elif isinstance(layer, Layer):
        layer_obj = layer
        fn = None
    else:
        layer_obj = None
        fn = layer

    assert input_spec or layer_obj is not None, "input_spec required"
    specs = input_spec or []
    specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
             for s in specs]

    params = ([(n, p) for n, p in layer_obj.named_parameters()]
              if layer_obj else [])
    buffers = ([(n, b) for n, b in layer_obj.named_buffers()]
               if layer_obj else [])
    consts = params + buffers
    const_arrays = [np.asarray(p._data) for _, p in consts]

    was_training = layer_obj.training if layer_obj else False
    if layer_obj:
        layer_obj.eval()

    def infer_fn(const_arrays_, *input_arrays):
        old = [p._data for _, p in consts]
        try:
            for (_, p), arr in zip(consts, const_arrays_):
                p._data = arr
            tensors = [Tensor._wrap(a) for a in input_arrays]
            with state.trace_guard():
                if layer_obj is not None:
                    out = layer_obj(*tensors)
                else:
                    out = fn(*tensors)
        finally:
            for (_, p), arr in zip(consts, old):
                p._data = arr
        out_flat, tree = jax.tree.flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        infer_fn._tree = tree
        return [o._data if isinstance(o, Tensor) else jnp.asarray(o)
                for o in out_flat]

    # dynamic (None/-1) dims become symbolic so the loaded program accepts
    # any size there (reference InputSpec semantics)
    scope = jax.export.SymbolicScope()
    example_inputs = []
    sym_counter = [0]

    def dim_str(s):
        if s == -1:
            sym_counter[0] += 1
            return f"_d{sym_counter[0]}"
        return str(s)

    for sp in specs:
        if any(s == -1 for s in sp.shape):
            shape = jax.export.symbolic_shape(
                ",".join(dim_str(s) for s in sp.shape), scope=scope)
        else:
            shape = tuple(sp.shape)
        example_inputs.append(jax.ShapeDtypeStruct(shape, sp.dtype))
    exported = jax.export.export(jax.jit(infer_fn))(
        [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in const_arrays],
        *example_inputs)
    payload = {
        "stablehlo": exported.serialize(),
        "consts": const_arrays,
        "const_names": [n for n, _ in consts],
        "specs": [(sp.shape, sp.dtype.name, sp.name) for sp in specs],
    }
    base = path
    with open(base + ".pdmodel", "wb") as f:
        pickle.dump(payload, f, protocol=4)
    from ..framework.io import save as fsave

    if layer_obj is not None:
        fsave(layer_obj.state_dict(), base + ".pdiparams")
        if was_training:
            layer_obj.train()


class TranslatedLayer(Layer):
    """Loaded compiled program (reference: translated_layer.py TranslatedLayer)."""

    def __init__(self, exported, consts, specs):
        super().__init__()
        self._exported = exported
        self._consts = consts
        self._specs = specs

    def forward(self, *inputs):
        arrays = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        outs = self._exported.call(self._consts, *arrays)
        outs = [Tensor._wrap(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs


def load(path, **configs):
    import pickle

    with open(path + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    exported = jax.export.deserialize(payload["stablehlo"])
    consts = [jnp.asarray(a) for a in payload["consts"]]
    return TranslatedLayer(exported, consts, payload["specs"])


_SOT_VERBOSITY = {"code_level": 0, "verbosity": 0}


def set_code_level(level=100, also_to_stdout=False):
    """Reference jit/sot debug knob (python/paddle/jit/sot/utils/envs.py):
    controls how much translated code is dumped. The trace-based to_static
    here has no bytecode translation stage; the setting is recorded and
    honored by to_static's trace logging."""
    _SOT_VERBOSITY["code_level"] = int(level)


def set_verbosity(level=0, also_to_stdout=False):
    _SOT_VERBOSITY["verbosity"] = int(level)
