"""Bucket-aware compile cache + telemetry for paddle.jit.

The reference framework absorbs variable-length batches with LoD tensors and
DataFeed (paddle/fluid/framework/data_feed.cc); this XLA-native design pads
instead. Without help, a stream of distinct sequence lengths costs one full
XLA compile *per distinct shape* — the classic recompile-per-shape cliff. The
standard fix in XLA-native stacks (GSPMD/PaLM-style static-shape input
pipelines) is to bucket incoming shapes to a small set of padded sizes so the
compile count is O(buckets), not O(distinct lengths).

This module is the jit-side half of that subsystem (the io-side half is
``paddle.io.BucketedBatchSampler``/``PadToBucket``):

- ``BucketSpec`` / ``set_shape_buckets``: registered bucket boundaries per
  axis. Incoming tensor shapes are padded UP to the nearest boundary before
  the compile-cache lookup, so every length in (prev_boundary, boundary]
  shares one executable. Lengths beyond the largest boundary pass through
  unchanged (and each costs its own compile — the telemetry below makes that
  visible instead of silent).
- per-function cache telemetry: compiles, cache hits, per-shape misses,
  eager-fallback invocations and bucket-pad counts, surfaced via
  ``paddle.jit.cache_stats()``. A ``FLAGS_jit_compile_warn_threshold``-gated
  warning fires when one function's compile count crosses the threshold —
  the actionable symptom of the cliff.

Padding here is zeros. That composes with mask-based variable-length code
(zero mask entries = padding) but is only registered explicitly — bucketing
is opt-in per function (``to_static(fn, shape_buckets=...)``) or global
(``set_shape_buckets``), never inferred.
"""

from __future__ import annotations

import bisect
import threading
import warnings

from ..core.flags import register_flag
from ..observability import metrics as _obs_metrics

register_flag(
    "jit_compile_warn_threshold", 8,
    help="warn when one jitted function has been XLA-compiled more than "
         "this many times (recompile-per-shape cliff); 0 disables. Fix by "
         "registering shape buckets (paddle.jit.set_shape_buckets) or "
         "bucketing the input pipeline (paddle.io.BucketedBatchSampler)")

__all__ = [
    "BucketSpec", "set_shape_buckets", "get_shape_buckets", "cache_stats",
    "reset_cache_stats", "CountingJit",
]


# --------------------------------------------------------------------------
# shape buckets
# --------------------------------------------------------------------------

class BucketSpec:
    """Registered pad-up boundaries per tensor axis.

    ``axes`` maps axis index -> strictly-increasing boundary tuple. The
    normalized forms accepted everywhere a spec is taken:

    - ``[64, 128, 256]``      -> buckets on axis 1 (the batch, seq layout)
    - ``{1: [64, 128]}``      -> explicit per-axis boundaries
    - a ``BucketSpec``        -> passed through
    """

    __slots__ = ("axes",)

    def __init__(self, axes):
        self.axes = {}
        for axis, bounds in axes.items():
            bounds = tuple(sorted(int(b) for b in bounds))
            if not bounds:
                raise ValueError("bucket boundaries must be non-empty")
            if any(b <= 0 for b in bounds):
                raise ValueError(f"bucket boundaries must be positive, got "
                                 f"{bounds}")
            if len(set(bounds)) != len(bounds):
                raise ValueError(f"duplicate bucket boundary in {bounds}")
            self.axes[int(axis)] = bounds

    @classmethod
    def normalize(cls, spec, default_axis=1):
        if spec is None or isinstance(spec, BucketSpec):
            return spec
        if isinstance(spec, dict):
            return cls(spec)
        return cls({default_axis: spec})

    def bucketed_dim(self, axis, size):
        """The boundary ``size`` pads up to on ``axis`` (``size`` itself when
        it exceeds every boundary — overflow stays unbucketed, visibly)."""
        bounds = self.axes.get(axis)
        if bounds is None:
            return size
        i = bisect.bisect_left(bounds, size)
        return bounds[i] if i < len(bounds) else size

    def pad_widths(self, shape):
        """[(lo, hi), ...] zero-pad widths taking ``shape`` to its bucket,
        or None when the shape is already on-bucket."""
        widths = [(0, 0)] * len(shape)
        changed = False
        for axis, size in enumerate(shape):
            target = self.bucketed_dim(axis, size)
            if target != size:
                widths[axis] = (0, target - size)
                changed = True
        return widths if changed else None

    def __repr__(self):
        return f"BucketSpec({self.axes})"


_GLOBAL_SPEC: BucketSpec | None = None


def set_shape_buckets(boundaries=None, axis=1):
    """Register process-global shape buckets for every jitted entry point
    (``to_static`` functions and ``fused_train_step``); ``None`` clears.
    Returns the previous spec. Per-function ``shape_buckets=`` overrides."""
    global _GLOBAL_SPEC
    prev = _GLOBAL_SPEC
    _GLOBAL_SPEC = (None if boundaries is None
                    else BucketSpec.normalize(boundaries, default_axis=axis))
    return prev


def get_shape_buckets():
    return _GLOBAL_SPEC


def infer_call_lengths(arrays, spec):
    """{axis: dominant length} for one call: the FIRST array carrying each
    bucketed axis defines the call's length on that axis (the ids-first
    convention, mirroring ``PadToBucket``'s field-selection rule). Only
    inputs MATCHING the dominant length are padded — fixed-size fields
    ([B, 1] labels, [B, n_features] dense vectors) pass through untouched
    instead of being silently corrupted with fabricated zeros."""
    lengths = {}
    for axis in spec.axes:
        for a in arrays:
            shape = getattr(a, "shape", None)
            if shape is not None and len(shape) > axis:
                lengths[axis] = int(shape[axis])
                break
    return lengths


def bucketed_call_shape(shape, spec, lengths):
    """``shape`` after pad-up under the dominant-length rule — the shape
    the compiled executable sees, computable WITHOUT materializing pads
    (cache-key lookups on the eager-fallback path stay allocation-free)."""
    out = list(shape)
    for axis, size in lengths.items():
        if axis < len(shape) and shape[axis] == size:
            out[axis] = spec.bucketed_dim(axis, size)
    return tuple(out)


def pad_array_to_bucket(arr, spec, lengths=None):
    """(possibly padded array, was_padded) for one jax/numpy array."""
    if lengths is None:
        lengths = infer_call_lengths([arr], spec)
    target = bucketed_call_shape(arr.shape, spec, lengths)
    if target == tuple(arr.shape):
        return arr, False
    import jax.numpy as jnp

    widths = [(0, t - s) for s, t in zip(arr.shape, target)]
    return jnp.pad(arr, widths), True


def tensor_leaves(tree):
    """Tensor leaves of an args/kwargs tree in call order."""
    from ..core.tensor import Tensor

    out = []

    def walk(x):
        if isinstance(x, Tensor):
            out.append(x)
        elif isinstance(x, (list, tuple)):
            for v in x:
                walk(v)
        elif isinstance(x, dict):
            for v in x.values():
                walk(v)

    walk(tree)
    return out


def infer_tree_lengths(tree, spec):
    return infer_call_lengths([t._data for t in tensor_leaves(tree)], spec)


def bucketize_tree(tree, spec, lengths=None, per_leaf=False):
    """Pad the padding-safe Tensor leaves of an args/kwargs tree up to
    their bucket. Only ``stop_gradient`` tensors are padded: a
    grad-requiring input must keep its identity so the autograd edge
    reaches the caller's tensor (padding data/ids/masks is the supported
    contract).

    Selection: by default the dominant-length rule (infer_call_lengths)
    decides which leaves pad; ``per_leaf=True`` pads every eligible leaf up
    on every registered axis unconditionally — the mode for subtrees the
    caller EXPLICITLY selected via ``bucket_args``. Returns
    (new_tree, n_padded)."""
    from ..core.tensor import Tensor

    if lengths is None and not per_leaf:
        lengths = infer_tree_lengths(tree, spec)
    n_padded = 0

    def walk(x):
        nonlocal n_padded
        if isinstance(x, Tensor):
            if not x.stop_gradient:
                return x
            arr, padded = pad_array_to_bucket(
                x._data, spec, None if per_leaf else lengths)
            if not padded:
                return x
            n_padded += 1
            t = Tensor._wrap(arr)
            t.stop_gradient = True
            return t
        if isinstance(x, tuple):
            return tuple(walk(v) for v in x)
        if isinstance(x, list):
            return [walk(v) for v in x]
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        return x

    return walk(tree), n_padded


# --------------------------------------------------------------------------
# compile-cache telemetry
# --------------------------------------------------------------------------

# registry-backed compile-cache counters (ISSUE 10): the numbers live in
# paddle.observability.metrics under a `function` label and cache_stats()
# is a thin backward-compatible view over them, so one Prometheus scrape
# sees the same compile/hit telemetry the dict API reports. Per-shape miss
# breakdowns stay in the local dict below — shape signatures are unbounded
# and the registry's label-cardinality rule forbids them as labels.
_M_COMPILES = _obs_metrics.counter(
    "jit_compiles_total", "XLA compiles per jitted entry point")
_M_HITS = _obs_metrics.counter(
    "jit_cache_hits_total", "compile-cache hits per jitted entry point")
_M_EAGER = _obs_metrics.counter(
    "jit_eager_fallbacks_total",
    "uncompiled per-call executions (the 10-100x cliff)")
_M_PADS = _obs_metrics.counter(
    "jit_bucket_pads_total", "inputs zero-padded up to a shape bucket")
_M_SCALER_FB = _obs_metrics.counter(
    "jit_scaler_fallbacks_total",
    "drive() calls degraded to per-step fetch by an enabled GradScaler")


class FunctionCacheStats:
    """Per-entry-point compile-cache counters (one per function name).

    The counter-valued fields are registry-backed (`jit_*_total{function=
    <name>}`); this object keeps only what the registry must not hold:
    the unbounded per-shape miss map and the one-shot warn latch."""

    __slots__ = ("name", "per_shape_misses", "_warned",
                 "host_blocked_ms", "queue_depth_sum", "queue_depth_n")

    def __init__(self, name):
        self.name = name
        self.per_shape_misses = {}
        self._warned = False
        # host-device overlap telemetry (DevicePrefetcher / drive): how
        # long the consumer blocked waiting on the transfer thread, and the
        # staged-batch queue depth sampled at each get (depth ~0 means the
        # host is the bottleneck, depth ~prefetch_depth means the device
        # is). Kept as the legacy name-keyed row; the authoritative
        # per-instance series are io_host_blocked_ms / io_queue_depth in
        # the registry (two same-named loaders no longer merge there).
        self.host_blocked_ms = 0.0
        self.queue_depth_sum = 0
        self.queue_depth_n = 0

    @property
    def compiles(self):
        return int(_M_COMPILES.value(function=self.name))

    @property
    def hits(self):
        return int(_M_HITS.value(function=self.name))

    @property
    def eager_fallbacks(self):
        return int(_M_EAGER.value(function=self.name))

    @property
    def bucket_pads(self):
        return int(_M_PADS.value(function=self.name))

    @property
    def scaler_fallbacks(self):
        return int(_M_SCALER_FB.value(function=self.name))

    def as_dict(self):
        return {
            "compiles": self.compiles,
            "hits": self.hits,
            "eager_fallbacks": self.eager_fallbacks,
            "bucket_pads": self.bucket_pads,
            "per_shape_misses": dict(self.per_shape_misses),
            "scaler_fallbacks": self.scaler_fallbacks,
            "host_blocked_ms": round(self.host_blocked_ms, 3),
            "avg_queue_depth": (
                round(self.queue_depth_sum / self.queue_depth_n, 3)
                if self.queue_depth_n else None),
        }


_LOCK = threading.RLock()
_STATS: dict[str, FunctionCacheStats] = {}


def _stats_for(name):
    with _LOCK:
        s = _STATS.get(name)
        if s is None:
            s = _STATS[name] = FunctionCacheStats(name)
        return s


def shape_signature(arrays):
    """Compact human-readable signature of a call's dynamic-input shapes,
    the per_shape_misses key."""
    return "|".join(
        f"{tuple(a.shape)}:{a.dtype}".replace(" ", "") for a in arrays)


def record_compile(name, shape_sig=""):
    from ..core.flags import flag_value

    s = _stats_for(name)
    _M_COMPILES.inc(function=name)
    with _LOCK:
        s.per_shape_misses[shape_sig] = \
            s.per_shape_misses.get(shape_sig, 0) + 1
        compiles, warned = s.compiles, s._warned
    threshold = int(flag_value("jit_compile_warn_threshold", 8))
    if threshold > 0 and compiles > threshold and not warned:
        with _LOCK:
            s._warned = True
        warnings.warn(
            f"jit compile cache: `{name}` has been XLA-compiled "
            f"{compiles} times (> FLAGS_jit_compile_warn_threshold="
            f"{threshold}) — a recompile-per-shape cliff. Bucket the "
            "input pipeline (paddle.io.BucketedBatchSampler + PadToBucket) "
            "or register pad-up buckets "
            "(paddle.jit.set_shape_buckets([64, 128, ...])) so the compile "
            "count is O(buckets). See paddle.jit.cache_stats() for the "
            "per-shape miss breakdown.", stacklevel=3)


def record_hit(name):
    _stats_for(name)
    _M_HITS.inc(function=name)


def record_eager_fallback(name):
    """Count one uncompiled (cached-eager) invocation and return a live
    RecordEvent span so the 10-100x per-call cliff is visible in profiler
    timelines — callers ``end()`` it after the eager call returns."""
    from ..profiler.utils import RecordEvent

    _stats_for(name)
    _M_EAGER.inc(function=name)
    return RecordEvent(f"jit::eager_fallback::{name}").begin()


def record_scaler_fallback(name):
    """Count one ``FusedTrainStep.drive`` call that degraded from
    deferred-window metric fetch to per-step fetch because an enabled
    GradScaler was attached (dynamic loss scaling consumes the finite
    flag every step)."""
    _stats_for(name)
    _M_SCALER_FB.inc(function=name)


def record_bucket_pads(name, n):
    if n:
        _stats_for(name)
        _M_PADS.inc(n, function=name)


def record_host_blocked(name, ms):
    """Count milliseconds the consumer spent blocked on the host input
    path (waiting for the prefetch thread to deliver a staged batch)."""
    with _LOCK:
        _stats_for(name).host_blocked_ms += float(ms)


def record_queue_depth(name, depth):
    """Sample the staged-batch queue depth at a consumer get — the direct
    gauge of who is the bottleneck (0 = host-bound, max = device-bound)."""
    with _LOCK:
        s = _stats_for(name)
        s.queue_depth_sum += int(depth)
        s.queue_depth_n += 1


def cache_stats(name=None):
    """Compile-cache telemetry for every jitted entry point.

    Returns ``{function_name: {"compiles", "hits", "eager_fallbacks",
    "bucket_pads", "per_shape_misses"}}`` — or one such dict when ``name``
    is given. ``compiles`` counts traces handed to XLA, ``hits`` are calls
    served by an already-compiled executable, ``eager_fallbacks`` counts
    uncompiled per-call executions (the 10-100x cliff), and
    ``per_shape_misses`` maps each missing input-shape signature to how many
    compiles it caused. ``host_blocked_ms`` / ``avg_queue_depth`` are the
    host-device overlap gauges recorded by ``io.DevicePrefetcher`` (time
    the consumer waited on the transfer thread; staged-queue depth at each
    get — 0 means host-bound, prefetch_depth means device-bound)."""
    with _LOCK:
        if name is not None:
            s = _STATS.get(name)
            return s.as_dict() if s is not None else None
        return {n: s.as_dict() for n, s in _STATS.items()}


def reset_cache_stats():
    """Drop all compile-cache counters (does NOT drop compiled executables).
    The registry-backed series behind cache_stats() are dropped too, so a
    re-registered function name restarts from zero."""
    with _LOCK:
        names = list(_STATS)
        _STATS.clear()
    for m in (_M_COMPILES, _M_HITS, _M_EAGER, _M_PADS, _M_SCALER_FB):
        for n in names:
            m.remove(function=n)


# --------------------------------------------------------------------------
# CountingJit — jax.jit with compile-cache telemetry
# --------------------------------------------------------------------------

class CountingJit:
    """``jax.jit`` wrapper whose compile/hit behavior is visible in
    ``paddle.jit.cache_stats()`` under ``name``.

    The serving engine and the llama decode loop dispatch hand-built pure
    functions (donated KV buffers, no autograd) that bypass
    ``StaticFunction`` — without this wrapper their compiles would be
    invisible and the "zero decode recompiles after warmup" acceptance
    unverifiable. A shape signature (array shapes/dtypes + static-arg
    values) not seen before means jax traces + XLA-compiles a fresh
    executable this dispatch; anything else is a cache hit — the same
    counting contract as ``FusedTrainStep._count_dispatch``.

    ``donate_argnums`` is honored only on TPU-class backends: XLA:CPU
    rejects donation with a warning per call, and the smoke tests run CPU.
    """

    __slots__ = ("name", "_jit", "_seen", "_static")

    def __init__(self, fn, name, static_argnums=(), donate_argnums=(),
                 jit_kwargs=None):
        import jax

        self.name = name
        self._static = tuple(static_argnums)
        if jax.default_backend() not in ("tpu", "axon"):
            donate_argnums = ()
        self._jit = jax.jit(fn, static_argnums=self._static,
                            donate_argnums=donate_argnums,
                            **(jit_kwargs or {}))
        self._seen = set()

    def lower(self, *args):
        return self._jit.lower(*args)

    def _signature(self, args):
        import jax

        arrays = []
        statics = []
        for i, a in enumerate(args):
            if i in self._static:
                statics.append(repr(a))
                continue
            leaves = jax.tree_util.tree_leaves(a)
            arrays.extend(l for l in leaves if hasattr(l, "shape"))
        sig = shape_signature(arrays)
        return sig + ("||" + "|".join(statics) if statics else "")

    def __call__(self, *args):
        sig = self._signature(args)
        if sig in self._seen:
            record_hit(self.name)
        else:
            self._seen.add(sig)
            record_compile(self.name, sig)
        return self._jit(*args)
