"""paddle.text datasets (reference python/paddle/text/datasets/*.py).

All reference datasets are downloader-backed; this environment has no
egress, so every class takes a local ``data_file`` path to the same archive
the reference downloads and parses it identically. Parsing happens on
host (numpy) — these feed DataLoaders, not the compiled path.
"""

from __future__ import annotations

import gzip
import os
import re
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]

from . import ViterbiDecoder, viterbi_decode  # noqa: E402,F401  (re-export)


def _need(path, what):
    if not path:
        raise ValueError(f"no network egress: {what} needs a local "
                        "data_file path to the reference archive")
    return path


class UCIHousing(Dataset):
    """reference text/datasets/uci_housing.py: 13 features + price,
    whitespace table, 80/20 train/test split, feature normalization."""

    def __init__(self, data_file=None, mode="train", download=True):
        _need(data_file, "UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32)
        feats = raw[:, :-1]
        mn, mx, avg = feats.min(0), feats.max(0), feats.mean(0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
        raw = np.concatenate([feats, raw[:, -1:]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """reference text/datasets/imdb.py: aclImdb tar -> (word ids, 0/1
    polarity); vocabulary built from the train split by frequency."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        _need(data_file, "Imdb")
        self._tar = tarfile.open(data_file)
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        texts, labels = [], []
        for m in self._tar.getmembers():
            mm = pat.match(m.name)
            if mm:
                texts.append(self._tar.extractfile(m).read().decode(
                    "utf-8", "ignore").lower())
                labels.append(0 if mm.group(1) == "pos" else 1)
        freq = {}
        for t in texts:
            for w in t.split():
                freq[w] = freq.get(w, 0) + 1
        words = sorted((w for w, c in freq.items() if c >= cutoff),
                       key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk)
                                 for w in t.split()], np.int64)
                     for t in texts]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """reference text/datasets/imikolov.py: PTB n-grams from the simple-
    examples tar."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        _need(data_file, "Imikolov")
        self._tar = tarfile.open(data_file)
        name = f"./simple-examples/data/ptb.{mode}.txt"
        member = next(m for m in self._tar.getmembers()
                      if m.name.endswith(f"ptb.{'train' if mode == 'train' else 'valid'}.txt"))
        text = self._tar.extractfile(member).read().decode()
        freq = {}
        for w in text.split():
            freq[w] = freq.get(w, 0) + 1
        words = sorted((w for w, c in freq.items() if c > min_word_freq),
                       key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        unk = self.word_idx.setdefault("<unk>", len(self.word_idx))
        eos = self.word_idx.setdefault("<e>", len(self.word_idx))
        self.data = []
        for line in text.split("\n"):
            ids = [self.word_idx.get(w, unk) for w in line.split()] + [eos]
            if data_type.upper() == "NGRAM":
                n = 5 if window_size < 0 else window_size
                for i in range(len(ids) - n + 1):
                    self.data.append(np.asarray(ids[i:i + n], np.int64))
            else:
                self.data.append(np.asarray(ids, np.int64))

    def __getitem__(self, idx):
        return tuple(self.data[idx])

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """reference text/datasets/movielens.py: ml-1m ratings joined with
    user/movie features."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        _need(data_file, "Movielens")
        import zipfile

        opener = zipfile.ZipFile if data_file.endswith(".zip") \
            else tarfile.open
        arc = opener(data_file)
        namelist = arc.namelist() if hasattr(arc, "namelist") \
            else [m.name for m in arc.getmembers()]

        def read(suffix):
            name = next(n for n in namelist if n.endswith(suffix))
            f = arc.open(name) if hasattr(arc, "open") \
                else arc.extractfile(name)
            return f.read().decode("latin1").strip().split("\n")

        users = {}
        for line in read("users.dat"):
            uid, gender, age, job, _ = line.split("::")
            users[int(uid)] = (0 if gender == "M" else 1, int(age), int(job))
        movies = {}
        for line in read("movies.dat"):
            mid, title, genres = line.split("::")
            movies[int(mid)] = (title, genres.split("|"))
        rng = np.random.RandomState(rand_seed)
        rows = []
        for line in read("ratings.dat"):
            uid, mid, rating, _ = line.split("::")
            uid, mid = int(uid), int(mid)
            if uid in users and mid in movies:
                rows.append((uid, *users[uid], mid, float(rating)))
        mask = rng.rand(len(rows)) < test_ratio
        self.rows = [r for r, m in zip(rows, mask)
                     if (m if mode == "test" else not m)]

    def __getitem__(self, idx):
        uid, gender, age, job, mid, rating = self.rows[idx]
        return (np.int64(uid), np.int64(gender), np.int64(age),
                np.int64(job), np.int64(mid),
                np.asarray([rating], np.float32))

    def __len__(self):
        return len(self.rows)


class Conll05st(Dataset):
    """reference text/datasets/conll05.py: SRL columns (word, predicate,
    label sequences as ids). Offline: pass the combined test split tar."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=True):
        _need(data_file, "Conll05st")
        self._sentences = []
        opener = gzip.open if data_file.endswith(".gz") else open
        with opener(data_file, "rt") as f:
            words, labels = [], []
            for line in f:
                line = line.strip()
                if not line:
                    if words:
                        self._sentences.append((words, labels))
                    words, labels = [], []
                else:
                    parts = line.split()
                    words.append(parts[0])
                    labels.append(parts[-1])
            if words:
                self._sentences.append((words, labels))
        vocab = sorted({w for ws, _ in self._sentences for w in ws})
        tags = sorted({t for _, ts in self._sentences for t in ts})
        self.word_dict = {w: i for i, w in enumerate(vocab)}
        self.label_dict = {t: i for i, t in enumerate(tags)}

    def __getitem__(self, idx):
        words, labels = self._sentences[idx]
        return (np.asarray([self.word_dict[w] for w in words], np.int64),
                np.asarray([self.label_dict[t] for t in labels], np.int64))

    def __len__(self):
        return len(self._sentences)


class _WMTBase(Dataset):
    BOS, EOS, UNK = "<s>", "<e>", "<unk>"

    def _build(self, pairs, dict_size):
        freq_src, freq_trg = {}, {}
        for s, t in pairs:
            for w in s:
                freq_src[w] = freq_src.get(w, 0) + 1
            for w in t:
                freq_trg[w] = freq_trg.get(w, 0) + 1

        def mk(freq):
            words = sorted(freq, key=lambda w: (-freq[w], w))
            vocab = [self.BOS, self.EOS, self.UNK] + words[:dict_size - 3]
            return {w: i for i, w in enumerate(vocab)}

        self.src_ids = mk(freq_src)
        self.trg_ids = mk(freq_trg)
        unk_s, unk_t = self.src_ids[self.UNK], self.trg_ids[self.UNK]
        self._items = []
        for s, t in pairs:
            src = [self.src_ids.get(w, unk_s) for w in s]
            trg = ([self.trg_ids[self.BOS]]
                   + [self.trg_ids.get(w, unk_t) for w in t])
            self._items.append(
                (np.asarray(src, np.int64), np.asarray(trg, np.int64),
                 np.asarray(trg[1:] + [self.trg_ids[self.EOS]], np.int64)))

    def __getitem__(self, idx):
        return self._items[idx]

    def __len__(self):
        return len(self._items)


class WMT14(_WMTBase):
    """reference text/datasets/wmt14.py: parallel fr-en pairs from the
    dev+test tar; lines are 'src ||| trg' or paired files."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        _need(data_file, "WMT14")
        pairs = _read_parallel_tar(data_file, mode)
        self._build(pairs, dict_size)


class WMT16(_WMTBase):
    """reference text/datasets/wmt16.py (en-de multi30k)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        _need(data_file, "WMT16")
        pairs = _read_parallel_tar(data_file, mode)
        if lang != "en":
            pairs = [(t, s) for s, t in pairs]
        self._build(pairs, max(src_dict_size, trg_dict_size))


def _read_parallel_tar(data_file, mode):
    """Accept either a tar of paired .src/.trg (or .en/.de) files, or a
    plain text file of 'src ||| trg' lines."""
    pairs = []
    if tarfile.is_tarfile(data_file):
        tf = tarfile.open(data_file)
        names = [m.name for m in tf.getmembers() if m.isfile()]
        cand = [n for n in names if mode in os.path.basename(n)]
        srcs = sorted(n for n in cand if n.endswith((".src", ".en")))
        trgs = sorted(n for n in cand if n.endswith((".trg", ".de", ".fr")))
        if srcs and trgs:
            s_lines = tf.extractfile(srcs[0]).read().decode(
                "utf-8", "ignore").strip().split("\n")
            t_lines = tf.extractfile(trgs[0]).read().decode(
                "utf-8", "ignore").strip().split("\n")
            pairs = [(s.split(), t.split())
                     for s, t in zip(s_lines, t_lines)]
    else:
        with open(data_file, encoding="utf-8") as f:
            for line in f:
                if "|||" in line:
                    s, t = line.split("|||", 1)
                    pairs.append((s.split(), t.split()))
    if not pairs:
        raise ValueError("could not locate parallel text for mode "
                         f"{mode!r} in {data_file}")
    return pairs
