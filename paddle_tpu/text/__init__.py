"""paddle.text — Viterbi decoding (reference python/paddle/text/
viterbi_decode.py:25 viterbi_decode + :100 ViterbiDecoder; the datasets/
subpackage is download-based and out of scope offline).

TPU-native: the DP forward pass is a ``lax.scan`` over time carrying the
per-tag best score, with argmax backpointers stacked by the scan; the
backtrace is a reverse scan over the backpointers — no data-dependent
Python control flow, fully jittable (the reference binds a CUDA kernel,
_C_ops.viterbi_decode).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..nn.layer.layers import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


@op("viterbi_decode_op", differentiable=False)
def _viterbi(potentials, trans, lengths, include_bos_eos_tag=True):
    b, t_max, n = potentials.shape
    lengths = lengths.astype(jnp.int32)
    pot = potentials.astype(jnp.float32)
    tr = trans.astype(jnp.float32)

    if include_bos_eos_tag:
        # last row/col = start tag, second-to-last = stop tag (reference)
        start_idx, stop_idx = n - 1, n - 2
        alpha0 = pot[:, 0, :] + tr[start_idx][None, :]
    else:
        alpha0 = pot[:, 0, :]

    def step(carry, inputs):
        alpha, t = carry
        emit = inputs  # [b, n]
        # scores[b, i, j] = alpha[b, i] + tr[i, j] + emit[b, j]
        scores = alpha[:, :, None] + tr[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)  # [b, n]
        best_score = jnp.max(scores, axis=1) + emit
        # sequences shorter than t keep their alpha frozen
        active = (t < lengths)[:, None]
        new_alpha = jnp.where(active, best_score, alpha)
        bp = jnp.where(active, best_prev,
                       jnp.broadcast_to(jnp.arange(n)[None, :], (b, n)))
        return (new_alpha, t + 1), bp

    (alpha, _), bps = jax.lax.scan(
        step, (alpha0, jnp.int32(1)),
        jnp.moveaxis(pot[:, 1:, :], 1, 0))  # [t_max-1, b, n]

    if include_bos_eos_tag:
        alpha = alpha + tr[:, stop_idx][None, :]
    scores = jnp.max(alpha, axis=1)
    last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)  # [b]

    def back(carry, bp):
        tag, t = carry
        # bp is for transition t -> t+1 (time index t in [1, t_max-1])
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        active = t < lengths
        new_tag = jnp.where(active, prev, tag)
        return (new_tag, t - 1), tag

    (first_tag, _), path_rev = jax.lax.scan(
        back, (last_tag, jnp.int32(t_max - 1)), bps, reverse=True)
    # path_rev[t] = tag at time t+1; the final carry is the tag at time 0
    paths = jnp.concatenate([first_tag[:, None],
                             jnp.moveaxis(path_rev, 0, 1)], axis=1)
    return scores, paths.astype(jnp.int64)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """reference text/viterbi_decode.py:25 — returns (scores [b],
    paths [b, t])."""
    return _viterbi(potentials, transition_params, lengths,
                    include_bos_eos_tag=bool(include_bos_eos_tag))


class ViterbiDecoder(Layer):
    """reference text/viterbi_decode.py:100."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


from .datasets import (  # noqa: E402,F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)

__all__ += ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
            "WMT14", "WMT16"]
