"""paddle.sparse — COO/CSR sparse tensors (reference: python/paddle/sparse/).

TPU-native design: COO wraps ``jax.experimental.sparse.BCOO`` — XLA's
batched-COO format with native sparse-dense matmul lowering (scatter/gather
on TPU) — rather than reimplementing the reference's SparseCooTensor C++
class (paddle/phi/core/sparse_coo_tensor.h). CSR is stored as
(crows, cols, values) and converts through COO for compute; on TPU the MXU
wants dense tiles anyway, so CSR is an interchange format, not a compute one.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "matmul", "add", "relu", "sin", "tanh", "sqrt",
           "square", "abs", "pow", "multiply", "is_same_shape"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(np.asarray(x))


class SparseCooTensor:
    """COO sparse tensor (ref paddle/phi/core/sparse_coo_tensor.h:1, python
    surface python/paddle/sparse/creation.py sparse_coo_tensor)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle Tensor-protocol surface --
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle: [ndim, nnz]

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        dense = np.asarray(self._bcoo.todense())
        return _dense_to_csr(dense)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor (ref paddle/phi/core/sparse_csr_tensor.h:1)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = _arr(crows).astype(jnp.int64)
        self._cols = _arr(cols).astype(jnp.int64)
        self._values = _arr(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def nnz(self):
        return int(self._values.shape[0])

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return Tensor(self._values)

    def to_dense(self):
        n_rows = self._shape[0]
        crows = np.asarray(self._crows)
        counts = np.diff(crows)
        rows = np.repeat(np.arange(n_rows), counts)
        dense = jnp.zeros(self._shape, self._values.dtype)
        dense = dense.at[rows, np.asarray(self._cols)].set(self._values)
        return Tensor(dense)

    def to_sparse_coo(self, sparse_dim=None):
        crows = np.asarray(self._crows)
        counts = np.diff(crows)
        rows = np.repeat(np.arange(self._shape[0]), counts)
        idx = jnp.stack([jnp.asarray(rows), self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx),
                                            shape=self._shape))

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def _dense_to_csr(dense):
    rows, cols = np.nonzero(dense)
    values = dense[rows, cols]
    crows = np.zeros(dense.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols, values, dense.shape)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """ref python/paddle/sparse/creation.py — indices [ndim, nnz]."""
    idx = np.asarray(_arr(indices)).astype(np.int64)
    vals = _arr(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    return SparseCooTensor(jsparse.BCOO((vals, jnp.asarray(idx.T)),
                                        shape=tuple(int(s) for s in shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = _arr(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    return SparseCsrTensor(crows, cols, vals, shape)


def _as_coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def matmul(x, y, name=None):
    """Sparse @ dense (ref python/paddle/sparse/binary.py matmul).
    Dense @ dense falls through to jnp."""
    x = _as_coo(x)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        raise NotImplementedError(
            "sparse.matmul supports sparse @ dense; for a sparse right "
            "operand densify it first (y.to_dense())")
    if isinstance(x, SparseCooTensor):
        out = x._bcoo @ _arr(y)
        return Tensor(out)
    return Tensor(_arr(x) @ _arr(y))


def masked_matmul(x, y, mask, name=None):
    """Dense @ dense, output only at mask's nonzeros (ref sparse/binary.py)."""
    mask = _as_coo(mask)
    prod = _arr(x) @ _arr(y)
    idx = mask._bcoo.indices
    vals = prod[idx[:, 0], idx[:, 1]]
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def add(x, y, name=None):
    x = _as_coo(x)
    y = _as_coo(y)
    xs = isinstance(x, SparseCooTensor)
    ys = isinstance(y, SparseCooTensor)
    if xs and ys:
        s = (x._bcoo + y._bcoo).sum_duplicates(nse=x._bcoo.nse + y._bcoo.nse)
        return SparseCooTensor(s)
    if xs or ys:  # mixed: densify (the result is dense anyway)
        xd = x.to_dense()._data if xs else _arr(x)
        yd = y.to_dense()._data if ys else _arr(y)
        return Tensor(xd + yd)
    return Tensor(_arr(x) + _arr(y))


def multiply(x, y, name=None):
    x = _as_coo(x)
    y = _as_coo(y)
    xs = isinstance(x, SparseCooTensor)
    ys = isinstance(y, SparseCooTensor)
    if xs and ys:
        return SparseCooTensor(jsparse.bcoo_multiply_sparse(x._bcoo, y._bcoo))
    if xs:  # sparse * dense/scalar broadcasts onto the nonzeros
        yd = _arr(y)
        if yd.ndim == 0:
            return SparseCooTensor(jsparse.BCOO(
                (x._bcoo.data * yd, x._bcoo.indices), shape=x._bcoo.shape))
        return SparseCooTensor(jsparse.bcoo_multiply_dense(x._bcoo, yd))
    if ys:
        return multiply(y, x)
    return Tensor(_arr(x) * _arr(y))


def _unary(name, fn):
    def api(x, name=None):
        x = _as_coo(x)
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(jsparse.BCOO(
                (fn(x._bcoo.data), x._bcoo.indices), shape=x._bcoo.shape))
        return Tensor(fn(_arr(x)))

    api.__name__ = name
    api.__doc__ = f"paddle.sparse.{name} — applied to nonzero values only."
    return api


relu = _unary("relu", lambda v: jnp.maximum(v, 0))
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)


def pow(x, factor, name=None):
    x = _as_coo(x)
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(jsparse.BCOO(
            (jnp.power(x._bcoo.data, factor), x._bcoo.indices),
            shape=x._bcoo.shape))
    return Tensor(jnp.power(_arr(x), factor))


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)
