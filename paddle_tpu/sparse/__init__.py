"""paddle.sparse — COO/CSR sparse tensors (reference: python/paddle/sparse/).

TPU-native design: COO wraps ``jax.experimental.sparse.BCOO`` — XLA's
batched-COO format with native sparse-dense matmul lowering (scatter/gather
on TPU) — rather than reimplementing the reference's SparseCooTensor C++
class (paddle/phi/core/sparse_coo_tensor.h). CSR is stored as
(crows, cols, values) and converts through COO for compute; on TPU the MXU
wants dense tiles anyway, so CSR is an interchange format, not a compute one.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "matmul", "add", "relu", "sin", "tanh", "sqrt",
           "square", "abs", "pow", "multiply", "is_same_shape"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(np.asarray(x))


class SparseCooTensor:
    """COO sparse tensor (ref paddle/phi/core/sparse_coo_tensor.h:1, python
    surface python/paddle/sparse/creation.py sparse_coo_tensor)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle Tensor-protocol surface --
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle: [ndim, nnz]

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        dense = np.asarray(self._bcoo.todense())
        return _dense_to_csr(dense)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor (ref paddle/phi/core/sparse_csr_tensor.h:1)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = _arr(crows).astype(jnp.int64)
        self._cols = _arr(cols).astype(jnp.int64)
        self._values = _arr(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def nnz(self):
        return int(self._values.shape[0])

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return Tensor(self._values)

    def to_dense(self):
        n_rows = self._shape[0]
        crows = np.asarray(self._crows)
        counts = np.diff(crows)
        rows = np.repeat(np.arange(n_rows), counts)
        dense = jnp.zeros(self._shape, self._values.dtype)
        dense = dense.at[rows, np.asarray(self._cols)].set(self._values)
        return Tensor(dense)

    def to_sparse_coo(self, sparse_dim=None):
        crows = np.asarray(self._crows)
        counts = np.diff(crows)
        rows = np.repeat(np.arange(self._shape[0]), counts)
        idx = jnp.stack([jnp.asarray(rows), self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx),
                                            shape=self._shape))

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def _dense_to_csr(dense):
    rows, cols = np.nonzero(dense)
    values = dense[rows, cols]
    crows = np.zeros(dense.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols, values, dense.shape)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """ref python/paddle/sparse/creation.py — indices [ndim, nnz]."""
    idx = np.asarray(_arr(indices)).astype(np.int64)
    vals = _arr(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    return SparseCooTensor(jsparse.BCOO((vals, jnp.asarray(idx.T)),
                                        shape=tuple(int(s) for s in shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = _arr(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    return SparseCsrTensor(crows, cols, vals, shape)


def _as_coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def matmul(x, y, name=None):
    """Sparse @ dense (ref python/paddle/sparse/binary.py matmul).
    Dense @ dense falls through to jnp."""
    x = _as_coo(x)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        raise NotImplementedError(
            "sparse.matmul supports sparse @ dense; for a sparse right "
            "operand densify it first (y.to_dense())")
    if isinstance(x, SparseCooTensor):
        out = x._bcoo @ _arr(y)
        return Tensor(out)
    return Tensor(_arr(x) @ _arr(y))


def masked_matmul(x, y, mask, name=None):
    """Dense @ dense, output only at mask's nonzeros (ref sparse/binary.py)."""
    mask = _as_coo(mask)
    prod = _arr(x) @ _arr(y)
    idx = mask._bcoo.indices
    vals = prod[idx[:, 0], idx[:, 1]]
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def add(x, y, name=None):
    x = _as_coo(x)
    y = _as_coo(y)
    xs = isinstance(x, SparseCooTensor)
    ys = isinstance(y, SparseCooTensor)
    if xs and ys:
        s = (x._bcoo + y._bcoo).sum_duplicates(nse=x._bcoo.nse + y._bcoo.nse)
        return SparseCooTensor(s)
    if xs or ys:  # mixed: densify (the result is dense anyway)
        xd = x.to_dense()._data if xs else _arr(x)
        yd = y.to_dense()._data if ys else _arr(y)
        return Tensor(xd + yd)
    return Tensor(_arr(x) + _arr(y))


def multiply(x, y, name=None):
    x = _as_coo(x)
    y = _as_coo(y)
    xs = isinstance(x, SparseCooTensor)
    ys = isinstance(y, SparseCooTensor)
    if xs and ys:
        return SparseCooTensor(jsparse.bcoo_multiply_sparse(x._bcoo, y._bcoo))
    if xs:  # sparse * dense/scalar broadcasts onto the nonzeros
        yd = _arr(y)
        if yd.ndim == 0:
            return SparseCooTensor(jsparse.BCOO(
                (x._bcoo.data * yd, x._bcoo.indices), shape=x._bcoo.shape))
        return SparseCooTensor(jsparse.bcoo_multiply_dense(x._bcoo, yd))
    if ys:
        return multiply(y, x)
    return Tensor(_arr(x) * _arr(y))


def _unary(name, fn):
    def api(x, name=None):
        x = _as_coo(x)
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(jsparse.BCOO(
                (fn(x._bcoo.data), x._bcoo.indices), shape=x._bcoo.shape))
        return Tensor(fn(_arr(x)))

    api.__name__ = name
    api.__doc__ = f"paddle.sparse.{name} — applied to nonzero values only."
    return api


relu = _unary("relu", lambda v: jnp.maximum(v, 0))
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)


def pow(x, factor, name=None):
    x = _as_coo(x)
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(jsparse.BCOO(
            (jnp.power(x._bcoo.data, factor), x._bcoo.indices),
            shape=x._bcoo.shape))
    return Tensor(jnp.power(_arr(x), factor))


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# round-4 parity additions (reference python/paddle/sparse/unary.py,
# binary.py, multiary.py)
# ---------------------------------------------------------------------------

tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
neg = _unary("neg", jnp.negative)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isnan = _unary("isnan", jnp.isnan)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """ref sparse/unary.py cast: dtypes of indices/values independently."""
    from ..core.dtype import convert_dtype

    x = _as_coo(x)
    idx = x._bcoo.indices
    vals = x._bcoo.data
    if index_dtype is not None:
        idx = idx.astype(convert_dtype(index_dtype))
    if value_dtype is not None:
        vals = vals.astype(convert_dtype(value_dtype))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=x._bcoo.shape))


def subtract(x, y, name=None):
    if np.isscalar(y):
        return add(x, -float(y))
    return add(x, multiply(y, Tensor(np.float32(-1.0))))


def divide(x, y, name=None):
    x = _as_coo(x)
    y = _as_coo(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        # reference: elementwise on the dense union (0/0 -> nan like dense)
        return Tensor(x.to_dense()._data / y.to_dense()._data)
    if isinstance(x, SparseCooTensor):
        yd = _arr(y)
        if yd.ndim == 0:
            return SparseCooTensor(jsparse.BCOO(
                (x._bcoo.data / yd, x._bcoo.indices), shape=x._bcoo.shape))
        return Tensor(x.to_dense()._data / yd)
    return Tensor(_arr(x) / _arr(y))


def transpose(x, perm, name=None):
    x = _as_coo(x)
    perm = [int(p) for p in perm]
    idx = x._bcoo.indices[:, np.asarray(perm)]
    shape = tuple(x._bcoo.shape[p] for p in perm)
    out = jsparse.BCOO((x._bcoo.data, idx), shape=shape)
    return SparseCooTensor(out.sum_duplicates(nse=out.nse))


def reshape(x, shape, name=None):
    x = _as_coo(x)
    old_shape = x._bcoo.shape
    shape = list(int(s) for s in shape)
    n = int(np.prod(old_shape))
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // max(known, 1)
    flat = jnp.ravel_multi_index(tuple(x._bcoo.indices.T), old_shape,
                                 mode="clip")
    new_idx = jnp.stack(jnp.unravel_index(flat, tuple(shape)), axis=1)
    return SparseCooTensor(jsparse.BCOO(
        (x._bcoo.data, new_idx), shape=tuple(shape)))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Dense-valued reduction (ref sparse/unary.py sum returns sparse; the
    dense result is its to_dense — documented deviation, values identical)."""
    from ..core.dtype import convert_dtype

    d = _as_coo(x).to_dense()._data
    out = jnp.sum(d, axis=None if axis is None else int(axis),
                  keepdims=keepdim)
    if dtype is not None:
        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def mv(x, vec, name=None):
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    prod = matmul(x, y)
    base = input.to_dense() if isinstance(
        input, (SparseCooTensor, SparseCsrTensor)) else input
    return Tensor(beta * _arr(base) + alpha * _arr(prod))


def coalesce(x, name=None):
    x = _as_coo(x)
    return SparseCooTensor(x._bcoo.sum_duplicates(nse=x._bcoo.nse))


_pyslice = slice  # capture the builtin before the paddle-named op shadows it


def slice(x, axes, starts, ends, name=None):  # noqa: A001 - reference name
    coo = _as_coo(x)
    d = coo.to_dense()._data
    idx = [_pyslice(None)] * d.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[int(a)] = _pyslice(int(s), int(e))
    sub = np.asarray(d[tuple(idx)])
    nz = np.stack(np.nonzero(sub), axis=0)
    return sparse_coo_tensor(nz, sub[tuple(nz)], shape=sub.shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized-free exact fallback (ref sparse/multiary.py pca_lowrank
    binds torch-style randomized SVD; exact SVD at these sizes is cheaper
    on TPU): returns (U, S, V) with q components."""
    d = _as_coo(x).to_dense()._data if isinstance(
        x, (SparseCooTensor, SparseCsrTensor)) else _arr(x)
    m, n = d.shape[-2], d.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        d = d - jnp.mean(d, axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(d.astype(jnp.float32), full_matrices=False)
    return (Tensor(u[..., :q]), Tensor(s[..., :q]),
            Tensor(jnp.swapaxes(vt, -1, -2)[..., :q]))


__all__ += [
    "tan", "asin", "atan", "sinh", "asinh", "atanh", "log1p", "expm1",
    "neg", "deg2rad", "rad2deg", "isnan", "cast", "subtract", "divide",
    "transpose", "reshape", "sum", "mv", "addmm", "coalesce", "slice",
    "pca_lowrank",
]
