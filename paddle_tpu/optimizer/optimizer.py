"""Optimizer base.

Reference: python/paddle/optimizer/optimizer.py. TPU-native design: each
``step()`` gathers (param, grad) arrays into one pytree and runs a single
jit-compiled update for the whole model — one XLA executable per step instead
of the reference's per-param kernel launches (its _C_ops.adam_ loop). The
update function is pure; parameter handles are rebound to the new arrays.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    _opt_name = "base"

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        assert parameters is not None, (
            "parameters is required in dygraph mode (pass model.parameters())"
        )
        self._parameter_list = list(parameters)
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self.regularization = weight_decay
        self._accumulators: dict[str, dict[int, jax.Array]] = {}
        self._global_step = 0
        self.helper = None

    # ------------- lr -------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate.get_lr())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is a scheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ------------- accumulators -------------
    def _acc(self, name, param, init=None):
        store = self._accumulators.setdefault(name, {})
        pid = id(param)
        if pid not in store:
            store[pid] = (jnp.zeros_like(param._data) if init is None
                          else init(param))
        return store[pid]

    def _set_acc(self, name, param, value):
        self._accumulators[name][id(param)] = value

    # ------------- main entry -------------
    def _collect_params_grads(self):
        pgs = []
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            g = p.grad
            if g is None:
                continue
            pgs.append((p, g))
        return pgs

    def _weight_decay_value(self, param):
        """L2Decay-style coupled decay (reference regularizer). Returns coeff."""
        reg = getattr(param, "regularizer", None) or self.regularization
        if reg is None:
            return 0.0
        if isinstance(reg, (int, float)):
            return float(reg)
        coeff = getattr(reg, "_coeff", None)
        if coeff is None:
            coeff = getattr(reg, "coeff", 0.0)
        return float(coeff)

    def step(self):
        pgs = self._collect_params_grads()
        if not pgs:
            return
        if self._grad_clip is not None:
            pgs = self._grad_clip(pgs)
        self._apply(pgs)
        self._global_step += 1

    def _apply(self, params_grads):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # ------------- checkpointing -------------
    def state_dict(self):
        sd = {}
        for name, store in self._accumulators.items():
            for p in self._parameter_list:
                if id(p) in store:
                    sd[f"{p.name}_{name}"] = Tensor._wrap(store[id(p)])
        sd["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        for name, store in self._accumulators.items():
            for p in self._parameter_list:
                key = f"{p.name}_{name}"
                if key in state_dict:
                    v = state_dict[key]
                    store[id(p)] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
        # re-init missing accumulators happens lazily on next step
        if "global_step" in state_dict:
            gs = state_dict["global_step"]
            self._global_step = int(gs.item() if isinstance(gs, Tensor) else gs)
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate,
                                                       LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    load_state_dict = set_state_dict

    def _create_accumulators(self, *a, **k):  # API parity
        pass
