"""Concrete optimizers: SGD, Momentum, Adagrad, Adam, AdamW, Adamax, Adadelta,
RMSProp, Lamb.

Reference: python/paddle/optimizer/{sgd,momentum,adam,adamw,lamb,...}.py and
PHI kernels paddle/phi/kernels/adam_kernel.h etc. Each optimizer's whole-model
update is one jitted pytree function (see optimizer.py docstring).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adagrad", "Adam", "AdamW", "Adamax", "Adadelta",
           "RMSProp", "Lamb"]


def _f32(x):
    return x.astype(jnp.float32)


@functools.partial(jax.jit, donate_argnums=(0,))
def _sgd_update(params, grads, lr, wds):
    def upd(p, g, wd):
        g = _f32(g) + wd * _f32(p)
        return (_f32(p) - lr * g).astype(p.dtype)

    return jax.tree.map(upd, params, grads, wds)


@functools.partial(jax.jit, donate_argnums=(0, 2), static_argnums=(5,))
def _momentum_update(params, grads, vels, lr, mu, use_nesterov, wds):
    def upd(p, g, v, wd):
        g = _f32(g) + wd * _f32(p)
        v_new = mu * v + g
        if use_nesterov:
            delta = g + mu * v_new
        else:
            delta = v_new
        return (_f32(p) - lr * delta).astype(p.dtype), v_new

    out = jax.tree.map(upd, params, grads, vels, wds)
    return (jax.tree.map(lambda t: t[0], out, is_leaf=lambda x: isinstance(x, tuple)),
            jax.tree.map(lambda t: t[1], out, is_leaf=lambda x: isinstance(x, tuple)))


@functools.partial(jax.jit, donate_argnums=(0, 2))
def _adagrad_update(params, grads, moments, lr, eps, wds):
    def upd(p, g, m, wd):
        g = _f32(g) + wd * _f32(p)
        m_new = m + g * g
        return (_f32(p) - lr * g / (jnp.sqrt(m_new) + eps)).astype(p.dtype), m_new

    out = jax.tree.map(upd, params, grads, moments, wds)
    return (jax.tree.map(lambda t: t[0], out, is_leaf=lambda x: isinstance(x, tuple)),
            jax.tree.map(lambda t: t[1], out, is_leaf=lambda x: isinstance(x, tuple)))


@functools.partial(jax.jit, donate_argnums=(0, 2, 3), static_argnums=(9,))
def _adam_update(params, grads, m1s, m2s, lr, beta1, beta2, eps, step,
                 mode, wd, lr_ratios):
    """mode: 'adam' (coupled L2 via grads), 'adamw' (decoupled decay)."""
    b1p = jnp.power(beta1, step)
    b2p = jnp.power(beta2, step)

    def upd(p, g, m1, m2, lr_ratio):
        gf = _f32(g)
        pf = _f32(p)
        if mode == "adam":
            gf = gf + wd * pf
        m1n = beta1 * m1 + (1 - beta1) * gf
        m2n = beta2 * m2 + (1 - beta2) * gf * gf
        m1h = m1n / (1 - b1p)
        m2h = m2n / (1 - b2p)
        step_lr = lr * lr_ratio
        new_p = pf - step_lr * m1h / (jnp.sqrt(m2h) + eps)
        if mode == "adamw":
            new_p = new_p - step_lr * wd * pf
        return new_p.astype(p.dtype), m1n, m2n

    out = jax.tree.map(upd, params, grads, m1s, m2s, lr_ratios)
    leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    return (jax.tree.map(lambda t: t[0], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[1], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[2], out, is_leaf=leaf))


class SGD(Optimizer):
    _opt_name = "sgd"

    def _apply(self, params_grads):
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        wds = [self._weight_decay_value(p) for p, _ in params_grads]
        lr = jnp.float32(self.get_lr())
        new = _sgd_update(params, grads, lr, wds)
        for (p, _), arr in zip(params_grads, new):
            p._rebind(arr)


class Momentum(Optimizer):
    _opt_name = "momentum"

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _apply(self, params_grads):
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        vels = [self._acc("velocity", p) for p, _ in params_grads]
        wds = [self._weight_decay_value(p) for p, _ in params_grads]
        lr = jnp.float32(self.get_lr())
        new_p, new_v = _momentum_update(params, grads, vels, lr,
                                        jnp.float32(self._momentum),
                                        self._use_nesterov, wds)
        for (p, _), arr, v in zip(params_grads, new_p, new_v):
            p._rebind(arr)
            self._set_acc("velocity", p, v)


class Adagrad(Optimizer):
    _opt_name = "adagrad"

    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _apply(self, params_grads):
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        init = lambda p: jnp.full_like(p._data, self._init_acc,  # noqa: E731
                                       dtype=jnp.float32)
        moments = [self._acc("moment", p, init) for p, _ in params_grads]
        wds = [self._weight_decay_value(p) for p, _ in params_grads]
        lr = jnp.float32(self.get_lr())
        new_p, new_m = _adagrad_update(params, grads, moments, lr,
                                       jnp.float32(self._epsilon), wds)
        for (p, _), arr, m in zip(params_grads, new_p, new_m):
            p._rebind(arr)
            self._set_acc("moment", p, m)


def lazy_adam_rows(param, m1, m2, ids, grads, upd_mask, lr, beta1, beta2,
                   eps, b1p, b2p, mode, wd, lr_ratio):
    """Lazy-mode Adam/AdamW over touched rows only (the reference's
    ``Adam(lazy_mode=True)`` / SelectedRows adam kernel, SparseCore-style):
    gather the touched rows of the table and both moments, run the exact
    dense update formula on them, scatter the results back. Untouched rows
    — table AND moments — are never read or written; bias correction uses
    the GLOBAL step (``b1p``/``b2p`` passed in), matching Paddle's lazy
    semantics.

    ``ids [K]`` are deduplicated row ids (``sparse_grad.segment_rows``)
    with ``grads [K, dim]`` their summed row gradients; ``upd_mask [K]``
    disables dead dedup slots (and, in the fused step's protect mode, a
    whole non-finite step). Masked slots alias row ``ids[0]`` and carry
    slot 0's OWN payload (its updated value, or its current value when
    slot 0 is itself masked), so every scatter write targeting one row is
    identical — deterministic regardless of scatter order.

    Pure function: shared verbatim by the in-graph FusedTrainStep route and
    the donated eager kernel below, so the two paths cannot drift."""
    if int(ids.shape[0]) == 0:
        return param, m1, m2
    safe = jnp.where(upd_mask, ids, ids[0])
    pf = _f32(param[safe])
    m1r = m1[safe]
    m2r = m2[safe]
    gf = _f32(grads)
    if mode == "adam":
        gf = gf + wd * pf
    m1n = beta1 * m1r + (1 - beta1) * gf
    m2n = beta2 * m2r + (1 - beta2) * gf * gf
    m1h = m1n / (1 - b1p)
    m2h = m2n / (1 - b2p)
    step_lr = lr * lr_ratio
    new = pf - step_lr * m1h / (jnp.sqrt(m2h) + eps)
    if mode == "adamw":
        new = new - step_lr * wd * pf
    mask = upd_mask[:, None]

    def settle(updated, current):
        # masked slot → keep current values; then masked slots (which all
        # alias row ids[0]) take slot 0's payload so duplicate writes agree
        base = jnp.where(mask, updated, current)
        return jnp.where(mask, base, base[0][None])

    return (param.at[safe].set(settle(new, pf).astype(param.dtype)),
            m1.at[safe].set(settle(m1n, m1r)),
            m2.at[safe].set(settle(m2n, m2r)))


@functools.partial(jax.jit, donate_argnums=(0, 1, 2), static_argnums=(10,))
def _adam_lazy_update(param, m1, m2, dense_grad, raw_ids, lr, beta1,
                      beta2, eps, step, mode, wd, lr_ratio):
    """Eager lazy-mode kernel: the autograd gradient is dense (the gather's
    backward scatter-adds into a vocab-sized buffer), but its live rows are
    known from the forward's recorded lookups — so only those rows are
    gathered here, and the table + moments see row traffic instead of three
    full-table streams. The id dedup runs IN here (one fused executable
    per step, not a string of eager dispatches); duplicate occurrences
    were already summed by the scatter-add, hence the plain row gather of
    each unique id (no re-summing)."""
    from ..ops.sparse_grad import unique_ids

    ids, valid = unique_ids(raw_ids)
    b1p = jnp.power(beta1, step)
    b2p = jnp.power(beta2, step)
    row_grads = jnp.take(dense_grad, jnp.where(valid, ids, ids[0]),
                         axis=0)
    return lazy_adam_rows(param, m1, m2, ids, row_grads, valid, lr,
                          beta1, beta2, eps, b1p, b2p, mode, wd, lr_ratio)


class _AdamBase(Optimizer):
    _mode = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, apply_decay_param_fun=None, lr_ratio=None,
                 **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        self._lazy_mode = bool(lazy_mode)
        self._multi_precision = bool(multi_precision)
        self._fallback_warned = set()
        if self._multi_precision:
            self._warn_fallback(
                "multi_precision",
                "multi_precision=True is not implemented on this backend; "
                "updates run the standard fp32-compute path (parameters "
                "cast up per step, no persistent master weights)")

    @property
    def lazy_mode(self):
        return self._lazy_mode

    @property
    def multi_precision(self):
        return self._multi_precision

    def _warn_fallback(self, key, msg):
        """Requested-but-unimplemented combination: say so ONCE per
        instance, then take the dense/standard path silently."""
        if key in self._fallback_warned:
            return
        self._fallback_warned.add(key)
        import warnings

        warnings.warn(f"{type(self).__name__}: {msg}", stacklevel=3)

    def _wd_coeff(self):
        wd = self.regularization
        if wd is None:
            return 0.01 if self._mode == "adamw" else 0.0
        if isinstance(wd, (int, float)):
            return float(wd)
        return float(getattr(wd, "_coeff", getattr(wd, "coeff", 0.0)))

    def _param_wd(self, p):
        wd = self._wd_coeff()
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            return 0.0
        return wd

    def _apply_lazy(self, params_grads):
        """Route params with recorded sparse lookups through the lazy row
        kernel; returns the (param, grad) pairs left for the dense path.
        A lazy table's update touches only the rows the forward looked up
        — untouched rows' moments stay untouched (no beta decay), exactly
        Paddle's lazy_mode semantics.

        Contract: the recorded lookup ids must cover the gradient's
        support — true when the table is used ONLY through
        ``SparseEmbedding`` lookups (the sole recorder). A table whose
        weight additionally feeds other ops (tied projections) must train
        with ``lazy_mode=False``; the fused-step route detects that case
        structurally and falls back per table, the eager path cannot see
        the rest of the graph and relies on this contract."""
        from ..ops import sparse_grad

        fp32_init = lambda p: jnp.zeros(p._data.shape, jnp.float32)  # noqa: E731
        rest = []
        step = jnp.float32(self._global_step + 1)
        for p, g in params_grads:
            ids = sparse_grad.consume_eager_lookups(p)
            if ids is None or p._data.ndim != 2 \
                    or g._data.shape != p._data.shape:
                rest.append((p, g))
                continue
            m1 = self._acc("moment1", p, fp32_init)
            m2 = self._acc("moment2", p, fp32_init)
            lr_ratio = (float(self._lr_ratio(p))
                        if self._lr_ratio is not None else 1.0)
            new_p, new_m1, new_m2 = _adam_lazy_update(
                p._data, m1, m2, g._data, ids,
                jnp.float32(self.get_lr()), jnp.float32(self._beta1),
                jnp.float32(self._beta2), jnp.float32(self._epsilon),
                step, self._mode, jnp.float32(self._param_wd(p)),
                jnp.float32(lr_ratio))
            p._rebind(new_p)
            self._set_acc("moment1", p, new_m1)
            self._set_acc("moment2", p, new_m2)
        return rest

    def state_dict(self):
        sd = super().state_dict()
        sd["lazy_mode"] = self._lazy_mode
        sd["multi_precision"] = self._multi_precision
        return sd

    def set_state_dict(self, state_dict):
        super().set_state_dict(state_dict)
        if "lazy_mode" in state_dict:
            self._lazy_mode = bool(state_dict["lazy_mode"])
        if "multi_precision" in state_dict:
            self._multi_precision = bool(state_dict["multi_precision"])

    load_state_dict = set_state_dict

    def _apply(self, params_grads):
        if self._lazy_mode:
            params_grads = self._apply_lazy(params_grads)
            if not params_grads:
                return
        fp32_init = lambda p: jnp.zeros(p._data.shape, jnp.float32)  # noqa: E731
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        m1s = [self._acc("moment1", p, fp32_init) for p, _ in params_grads]
        m2s = [self._acc("moment2", p, fp32_init) for p, _ in params_grads]
        wd = self._wd_coeff()
        lr_ratios = []
        for p, _ in params_grads:
            r = 1.0
            if self._apply_decay_param_fun is not None and \
                    not self._apply_decay_param_fun(p.name):
                # paddle semantics: decay only applies to selected params.
                # encode via per-param wd by zeroing through lr_ratio trick:
                # handled below by per-param wd list instead.
                pass
            if self._lr_ratio is not None:
                r = float(self._lr_ratio(p))
            lr_ratios.append(jnp.float32(r))
        lr = jnp.float32(self.get_lr())
        step = jnp.float32(self._global_step + 1)
        if self._apply_decay_param_fun is not None:
            # split params into decayed / undecayed groups, two jit calls
            dec_idx = [i for i, (p, _) in enumerate(params_grads)
                       if self._apply_decay_param_fun(p.name)]
            und_idx = [i for i in range(len(params_grads)) if i not in dec_idx]
            for idx, w in ((dec_idx, wd), (und_idx, 0.0)):
                if not idx:
                    continue
                sub = lambda xs: [xs[i] for i in idx]  # noqa: E731
                new_p, new_m1, new_m2 = _adam_update(
                    sub(params), sub(grads), sub(m1s), sub(m2s), lr,
                    jnp.float32(self._beta1), jnp.float32(self._beta2),
                    jnp.float32(self._epsilon), step, self._mode,
                    jnp.float32(w), sub(lr_ratios))
                for j, i in enumerate(idx):
                    p = params_grads[i][0]
                    p._rebind(new_p[j])
                    self._set_acc("moment1", p, new_m1[j])
                    self._set_acc("moment2", p, new_m2[j])
            return
        new_p, new_m1, new_m2 = _adam_update(
            params, grads, m1s, m2s, lr, jnp.float32(self._beta1),
            jnp.float32(self._beta2), jnp.float32(self._epsilon), step,
            self._mode, jnp.float32(wd), lr_ratios)
        for (p, _), arr, m1, m2 in zip(params_grads, new_p, new_m1, new_m2):
            p._rebind(arr)
            self._set_acc("moment1", p, m1)
            self._set_acc("moment2", p, m2)


class Adam(_AdamBase):
    _opt_name = "adam"
    _mode = "adam"


class AdamW(_AdamBase):
    _opt_name = "adamw"
    _mode = "adamw"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None, **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name, apply_decay_param_fun, lr_ratio)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adamax_update(params, grads, m1s, infs, lr, beta1, beta2, eps, step, wds):
    b1p = jnp.power(beta1, step)

    def upd(p, g, m, inf, wd):
        gf = _f32(g) + wd * _f32(p)
        m_new = beta1 * m + (1 - beta1) * gf
        inf_new = jnp.maximum(beta2 * inf, jnp.abs(gf))
        new_p = _f32(p) - lr / (1 - b1p) * m_new / (inf_new + eps)
        return new_p.astype(p.dtype), m_new, inf_new

    out = jax.tree.map(upd, params, grads, m1s, infs, wds)
    leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    return (jax.tree.map(lambda t: t[0], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[1], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[2], out, is_leaf=leaf))


class Adamax(Optimizer):
    _opt_name = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _apply(self, params_grads):
        fp32_init = lambda p: jnp.zeros(p._data.shape, jnp.float32)  # noqa: E731
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        m1s = [self._acc("moment", p, fp32_init) for p, _ in params_grads]
        infs = [self._acc("inf_norm", p, fp32_init) for p, _ in params_grads]
        wds = [self._weight_decay_value(p) for p, _ in params_grads]
        new_p, new_m, new_i = _adamax_update(
            params, grads, m1s, infs, jnp.float32(self.get_lr()),
            jnp.float32(self._beta1), jnp.float32(self._beta2),
            jnp.float32(self._epsilon), jnp.float32(self._global_step + 1), wds)
        for (p, _), arr, m, i in zip(params_grads, new_p, new_m, new_i):
            p._rebind(arr)
            self._set_acc("moment", p, m)
            self._set_acc("inf_norm", p, i)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _adadelta_update(params, grads, avg_sq, avg_dx, lr, rho, eps, wds):
    def upd(p, g, asq, adx, wd):
        gf = _f32(g) + wd * _f32(p)
        asq_n = rho * asq + (1 - rho) * gf * gf
        dx = jnp.sqrt(adx + eps) / jnp.sqrt(asq_n + eps) * gf
        adx_n = rho * adx + (1 - rho) * dx * dx
        return (_f32(p) - lr * dx).astype(p.dtype), asq_n, adx_n

    out = jax.tree.map(upd, params, grads, avg_sq, avg_dx, wds)
    leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    return (jax.tree.map(lambda t: t[0], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[1], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[2], out, is_leaf=leaf))


class Adadelta(Optimizer):
    _opt_name = "adadelta"

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _apply(self, params_grads):
        fp32_init = lambda p: jnp.zeros(p._data.shape, jnp.float32)  # noqa: E731
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        asq = [self._acc("avg_squared_grad", p, fp32_init) for p, _ in params_grads]
        adx = [self._acc("avg_squared_update", p, fp32_init) for p, _ in params_grads]
        wds = [self._weight_decay_value(p) for p, _ in params_grads]
        new_p, n_asq, n_adx = _adadelta_update(
            params, grads, asq, adx, jnp.float32(self.get_lr()),
            jnp.float32(self._rho), jnp.float32(self._epsilon), wds)
        for (p, _), arr, a, b in zip(params_grads, new_p, n_asq, n_adx):
            p._rebind(arr)
            self._set_acc("avg_squared_grad", p, a)
            self._set_acc("avg_squared_update", p, b)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3), static_argnums=(8,))
def _rmsprop_update(params, grads, means, moms, lr, rho, eps, momentum,
                    centered, mgs, wds):
    def upd(p, g, ms, mom, mg, wd):
        gf = _f32(g) + wd * _f32(p)
        ms_n = rho * ms + (1 - rho) * gf * gf
        if centered:
            mg_n = rho * mg + (1 - rho) * gf
            denom = jnp.sqrt(ms_n - mg_n * mg_n + eps)
        else:
            mg_n = mg
            denom = jnp.sqrt(ms_n + eps)
        mom_n = momentum * mom + lr * gf / denom
        return (_f32(p) - mom_n).astype(p.dtype), ms_n, mom_n, mg_n

    out = jax.tree.map(upd, params, grads, means, moms, mgs, wds)
    leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    return tuple(jax.tree.map(lambda t, i=i: t[i], out, is_leaf=leaf)
                 for i in range(4))


class RMSProp(Optimizer):
    _opt_name = "rmsprop"

    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _apply(self, params_grads):
        fp32_init = lambda p: jnp.zeros(p._data.shape, jnp.float32)  # noqa: E731
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        means = [self._acc("mean_square", p, fp32_init) for p, _ in params_grads]
        moms = [self._acc("momentum_acc", p, fp32_init) for p, _ in params_grads]
        mgs = [self._acc("mean_grad", p, fp32_init) for p, _ in params_grads]
        wds = [self._weight_decay_value(p) for p, _ in params_grads]
        new_p, n_ms, n_mom, n_mg = _rmsprop_update(
            params, grads, means, moms, jnp.float32(self.get_lr()),
            jnp.float32(self._rho), jnp.float32(self._epsilon),
            jnp.float32(self._momentum), self._centered, mgs, wds)
        for (p, _), arr, a, b, c in zip(params_grads, new_p, n_ms, n_mom, n_mg):
            p._rebind(arr)
            self._set_acc("mean_square", p, a)
            self._set_acc("momentum_acc", p, b)
            self._set_acc("mean_grad", p, c)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3), static_argnums=(10,))
def _lamb_update(params, grads, m1s, m2s, lr, beta1, beta2, eps, wd, step,
                 excludes):
    excludes = list(excludes)
    b1p = jnp.power(beta1, step)
    b2p = jnp.power(beta2, step)

    def upd(p, g, m1, m2, exclude):
        gf = _f32(g)
        pf = _f32(p)
        m1n = beta1 * m1 + (1 - beta1) * gf
        m2n = beta2 * m2 + (1 - beta2) * gf * gf
        m1h = m1n / (1 - b1p)
        m2h = m2n / (1 - b2p)
        r = m1h / (jnp.sqrt(m2h) + eps)
        if not exclude:
            r = r + wd * pf
        w_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (pf - lr * trust * r).astype(p.dtype), m1n, m2n

    out = jax.tree.map(upd, params, grads, m1s, m2s, excludes)
    leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    return (jax.tree.map(lambda t: t[0], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[1], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[2], out, is_leaf=leaf))


class Lamb(Optimizer):
    _opt_name = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply(self, params_grads):
        fp32_init = lambda p: jnp.zeros(p._data.shape, jnp.float32)  # noqa: E731
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        m1s = [self._acc("moment1", p, fp32_init) for p, _ in params_grads]
        m2s = [self._acc("moment2", p, fp32_init) for p, _ in params_grads]
        excludes = [bool(self._exclude_fn(p)) if self._exclude_fn else False
                    for p, _ in params_grads]
        new_p, n1, n2 = _lamb_update(
            params, grads, m1s, m2s, jnp.float32(self.get_lr()),
            jnp.float32(self._beta1), jnp.float32(self._beta2),
            jnp.float32(self._epsilon), jnp.float32(self._wd),
            jnp.float32(self._global_step + 1), tuple(excludes))
        for (p, _), arr, a, b in zip(params_grads, new_p, n1, n2):
            p._rebind(arr)
            self._set_acc("moment1", p, a)
            self._set_acc("moment2", p, b)


@functools.partial(jax.jit, donate_argnums=(0, 2, 3))
def _rprop_update(params, grads, prevs, steps, lr_min, lr_max, eta_neg,
                  eta_pos):
    """Resilient backprop (reference optimizer/rprop.py): per-element step
    sizes grow where successive grads agree in sign, shrink where they
    flip; flipped elements skip the update (grad zeroed)."""

    def upd(p, g, prev, step):
        gf = _f32(g)
        sign = jnp.sign(gf * prev)
        step_new = jnp.clip(
            jnp.where(sign > 0, step * eta_pos,
                      jnp.where(sign < 0, step * eta_neg, step)),
            lr_min, lr_max)
        g_eff = jnp.where(sign < 0, 0.0, gf)
        new_p = (_f32(p) - jnp.sign(g_eff) * step_new).astype(p.dtype)
        return new_p, g_eff, step_new

    out = jax.tree.map(upd, params, grads, prevs, steps)
    leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    return (jax.tree.map(lambda t: t[0], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[1], out, is_leaf=leaf),
            jax.tree.map(lambda t: t[2], out, is_leaf=leaf))


class Rprop(Optimizer):
    _opt_name = "rprop"

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._lr_range = (float(learning_rate_range[0]),
                          float(learning_rate_range[1]))
        self._etas = (float(etas[0]), float(etas[1]))

    def _apply(self, params_grads):
        init_step = lambda p: jnp.full(  # noqa: E731
            p._data.shape, float(self.get_lr()), jnp.float32)
        params = [p._data for p, _ in params_grads]
        grads = [g._data for _, g in params_grads]
        prevs = [self._acc("rprop_prev", p) for p, _ in params_grads]
        steps = [self._acc("rprop_step", p, init_step)
                 for p, _ in params_grads]
        new_p, new_prev, new_step = _rprop_update(
            params, grads, prevs, steps,
            jnp.float32(self._lr_range[0]), jnp.float32(self._lr_range[1]),
            jnp.float32(self._etas[0]), jnp.float32(self._etas[1]))
        for (p, _), arr, pr, st in zip(params_grads, new_p, new_prev,
                                       new_step):
            p._rebind(arr)
            self._set_acc("rprop_prev", p, pr)
            self._set_acc("rprop_step", p, st)


class LBFGS(Optimizer):
    """Limited-memory BFGS (reference optimizer/lbfgs.py): closure-based
    ``step(closure)`` with two-loop-recursion direction and backtracking
    Armijo line search (the reference's strong_wolfe option also accepts
    None == fixed step; backtracking sits between the two and keeps the
    whole step host-driven, which is fine — LBFGS is a full-batch
    optimizer, each closure call is one compiled forward/backward)."""

    _opt_name = "lbfgs"

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._max_iter = int(max_iter)
        self._tol_grad = float(tolerance_grad)
        self._tol_change = float(tolerance_change)
        self._history = int(history_size)
        self._line_search = line_search_fn
        self._s, self._y = [], []
        self._prev_flat_grad = None

    # ---- flat helpers ----
    def _flat(self, arrs):
        return jnp.concatenate([jnp.ravel(_f32(a)) for a in arrs])

    def _assign(self, flat):
        import numpy as np

        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p.shape))
            chunk = flat[off:off + n].reshape(p._data.shape)
            p._rebind(chunk.astype(p._data.dtype))
            off += n

    def _gather_grad(self):
        return self._flat([p.grad._data for p in self._parameter_list])

    def _direction(self, g):
        q = g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / (jnp.dot(y, s) + 1e-10)
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append((rho, a, s, y))
        if self._s:
            s, y = self._s[-1], self._y[-1]
            q = q * (jnp.dot(s, y) / (jnp.dot(y, y) + 1e-10))
        for rho, a, s, y in reversed(alphas):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        return -q

    def step(self, closure=None):
        assert closure is not None, "LBFGS.step needs a closure"
        loss = closure()
        flat_g = self._gather_grad()
        flat_x = self._flat([p._data for p in self._parameter_list])
        for _ in range(self._max_iter):
            if float(jnp.max(jnp.abs(flat_g))) <= self._tol_grad:
                break
            d = self._direction(flat_g)
            t = float(self.get_lr())
            if self._line_search in ("strong_wolfe", "backtracking"):
                f0 = float(loss.numpy())
                gtd = float(jnp.dot(flat_g, d))
                for _ls in range(20):
                    self._assign(flat_x + t * d)
                    self.clear_grad()
                    loss = closure()
                    if float(loss.numpy()) <= f0 + 1e-4 * t * gtd:
                        break
                    t *= 0.5
            else:
                self._assign(flat_x + t * d)
                self.clear_grad()
                loss = closure()
            new_g = self._gather_grad()
            new_x = self._flat([p._data for p in self._parameter_list])
            s, y = new_x - flat_x, new_g - flat_g
            if float(jnp.dot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self._history:
                    self._s.pop(0)
                    self._y.pop(0)
            if float(jnp.max(jnp.abs(new_x - flat_x))) < self._tol_change:
                flat_x, flat_g = new_x, new_g
                break
            flat_x, flat_g = new_x, new_g
        return loss


__all__ += ["Rprop", "LBFGS"]
