"""paddle.fft — spectral transforms (reference: python/paddle/fft.py).

Thin dispatch layer over jnp.fft: XLA lowers FFTs to the backend's native
implementation (DUCC on CPU, the TPU FFT lowering on device). Norm-mode
semantics ("backward"/"ortho"/"forward") match the reference, which follows
numpy.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .core.dispatch import op
from .core.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm not in (None, "backward", "ortho", "forward"):
        raise ValueError(f"invalid norm {norm!r}")
    return norm or "backward"


_COMPLEX_OK = None


def _complex_ok():
    """Probe once whether the default backend supports complex FFT +
    host transfer. Production TPU/XLA does; the experimental axon tunnel
    plugin in this image does not — there eager calls fall back to numpy on
    the host (correct values, no autodiff through the fallback)."""
    global _COMPLEX_OK
    if _COMPLEX_OK is None:
        try:
            import jax

            # identify by platform string — actually RUNNING a complex op to
            # probe would enqueue an unimplemented program and poison the
            # device stream on the very backend being probed
            version = jax._src.xla_bridge.get_backend().platform_version
            _COMPLEX_OK = "axon" not in version.lower()
        except Exception:
            _COMPLEX_OK = True
    return _COMPLEX_OK


def _eager_array(x):
    """The host value for the numpy fallback, or None if x is traced.

    The fallback is a host-side detour: it cannot carry gradients. Rather
    than let them vanish silently, refuse when the input participates in a
    live tape (stop_gradient=False under grad-enabled eager mode)."""
    import jax

    from .core import state as _state

    data = x._data if isinstance(x, Tensor) else x
    if isinstance(data, jax.core.Tracer):
        return None
    if (isinstance(x, Tensor) and not x.stop_gradient
            and _state.grad_enabled()):
        raise RuntimeError(
            "fft numpy fallback (complex-incapable backend) cannot "
            "differentiate: input has stop_gradient=False. Detach the input "
            "or wrap the call in paddle.no_grad().")
    return np.asarray(data)


def _mk1(name):
    fn = getattr(jnp.fft, name)

    @op(f"fft_{name}")
    def _impl(x, n=None, axis=-1, norm="backward"):
        return fn(x, n=n, axis=axis, norm=norm)

    np_fn = getattr(np.fft, name)

    def api(x, n=None, axis=-1, norm="backward", name=None):
        if not _complex_ok():
            host = _eager_array(x)
            if host is not None:
                # keep the host value un-device_put (complex transfer is
                # what the backend lacks)
                return Tensor._wrap(np_fn(host, n=n, axis=int(axis),
                                          norm=_norm(norm)))
        return _impl(x, n=None if n is None else int(n), axis=int(axis),
                     norm=_norm(norm))

    api.__name__ = name
    api.__doc__ = f"paddle.fft.{name} (jnp.fft.{name} under dispatch)."
    return api


def _mkn(name, ref_name):
    fn = getattr(jnp.fft, name)

    @op(f"fft_{name}")
    def _impl(x, s=None, axes=None, norm="backward"):
        return fn(x, s=s, axes=axes, norm=norm)

    np_fn = getattr(np.fft, name)

    def api(x, s=None, axes=None, norm="backward", name=None):
        if not _complex_ok():
            host = _eager_array(x)
            if host is not None:
                return Tensor._wrap(np_fn(host, s=s, axes=axes,
                                          norm=_norm(norm)))
        return _impl(x, s=None if s is None else tuple(int(v) for v in s),
                     axes=None if axes is None else tuple(int(a)
                                                          for a in axes),
                     norm=_norm(norm))

    api.__name__ = ref_name
    api.__doc__ = f"paddle.fft.{ref_name} (jnp.fft.{name} under dispatch)."
    return api


fft = _mk1("fft")
ifft = _mk1("ifft")
rfft = _mk1("rfft")
irfft = _mk1("irfft")
hfft = _mk1("hfft")
ihfft = _mk1("ihfft")

fftn = _mkn("fftn", "fftn")
ifftn = _mkn("ifftn", "ifftn")
rfftn = _mkn("rfftn", "rfftn")
irfftn = _mkn("irfftn", "irfftn")


def _mk2(nd_api, ref_name):
    def api(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return nd_api(x, s=s, axes=axes, norm=norm)

    api.__name__ = ref_name
    return api


fft2 = _mk2(fftn, "fft2")
ifft2 = _mk2(ifftn, "ifft2")
rfft2 = _mk2(rfftn, "rfft2")
irfft2 = _mk2(irfftn, "irfft2")


def fftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(np.fft.fftfreq(int(n), d).astype(dtype))


def rfftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(np.fft.rfftfreq(int(n), d).astype(dtype))


@op("fftshift")
def _fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


@op("ifftshift")
def _ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


def _is_complex(x):
    data = x._data if isinstance(x, Tensor) else x
    return np.issubdtype(np.dtype(str(getattr(data, "dtype", "float32"))),
                         np.complexfloating)


def fftshift(x, axes=None, name=None):
    # shift is a pure roll — only complex INPUTS need the host detour, so a
    # real differentiable input keeps the (differentiable) device path
    if not _complex_ok() and _is_complex(x):
        host = _eager_array(x)
        if host is not None:
            return Tensor._wrap(np.fft.fftshift(host, axes=axes))
    return _fftshift(x, axes=None if axes is None else tuple(
        int(a) for a in np.atleast_1d(axes)))


def ifftshift(x, axes=None, name=None):
    if not _complex_ok() and _is_complex(x):
        host = _eager_array(x)
        if host is not None:
            return Tensor._wrap(np.fft.ifftshift(host, axes=axes))
    return _ifftshift(x, axes=None if axes is None else tuple(
        int(a) for a in np.atleast_1d(axes)))


def _resolve_axes(x, axes, n_default=2):
    if axes is None:
        nd = len(x.shape)
        return tuple(range(nd - n_default, nd))
    return tuple(int(a) for a in axes)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D FFT of a signal hermitian-symmetric along the LAST axis
    (reference python/paddle/fft.py hfft2): c2c FFT over the leading axis,
    hermitian c2r over the last — the mirror is only on the final axis, so
    the composition is exact (norm factors multiply per-axis)."""
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    axes = _resolve_axes(x, axes, n_default=len(x.shape))
    s_rest = None if s is None else list(s[:-1])
    y = x
    if len(axes) > 1:
        y = fftn(y, s=s_rest, axes=list(axes[:-1]), norm=norm)
    return hfft(y, n=None if s is None else int(s[-1]), axis=axes[-1],
                norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    axes = _resolve_axes(x, axes, n_default=len(x.shape))
    y = ihfft(x, n=None if s is None else int(s[-1]), axis=axes[-1],
              norm=norm)
    if len(axes) > 1:
        s_rest = None if s is None else list(s[:-1])
        y = ifftn(y, s=s_rest, axes=list(axes[:-1]), norm=norm)
    return y


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
