"""WAV file IO over the stdlib ``wave`` module.

Reference: python/paddle/audio/backends/wave_backend.py:1 — the reference
also ships a pure wave-module backend as the no-dependency default (its
soundfile backend is an optional install, absent in this image)."""

from __future__ import annotations

import wave

import numpy as np

from ...core.tensor import Tensor

__all__ = ["load", "save", "info"]


class AudioInfo:
    def __init__(self, sample_rate, num_frames, num_channels, bits_per_sample):
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample


def info(filepath):
    with wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (waveform Tensor [C, T] (or [T, C]), sample_rate)."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dt = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dt).reshape(-1, ch)
    if width == 1:
        data = data.astype(np.int16) - 128  # 8-bit wav is unsigned
        scale = 128.0
    else:
        scale = float(2 ** (8 * width - 1))
    if normalize:
        data = data.astype(np.float32) / scale
    arr = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         bits_per_sample=16):
    if bits_per_sample != 16:
        raise ValueError("wave backend writes 16-bit PCM only")
    arr = np.asarray(src._data if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T  # -> [T, C]
    if arr.ndim == 1:
        arr = arr[:, None]
    if arr.dtype.kind == "f":
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * 32767.0).astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(arr).tobytes())
