"""Audio IO backends (reference audio/backends/__init__.py). Only the
no-dependency wave backend ships (the reference's soundfile backend is an
optional extra, not present in this image)."""

from . import wave_backend  # noqa: F401
from .wave_backend import info, load, save  # noqa: F401


def list_available_backends():
    return ["wave"]


def get_current_backend():
    return "wave"


def set_backend(backend_name):
    if backend_name != "wave":
        raise NotImplementedError(
            "only the stdlib 'wave' backend is available (soundfile is an "
            "optional dependency not present in this image)")


__all__ = ["load", "save", "info", "list_available_backends",
           "get_current_backend", "set_backend"]
