"""Window functions (reference python/paddle/audio/functional/window.py:335
``get_window`` and the private per-window builders). Pure numpy — windows
are tiny host-side constants baked into the graph."""

from __future__ import annotations

import math

import numpy as np

from ...core.tensor import Tensor

__all__ = ["get_window"]


def _extend(M, sym):
    return (M, False) if sym else (M + 1, True)


def _truncate(w, needs_trunc):
    return w[:-1] if needs_trunc else w


def _general_cosine(M, a, sym):
    if M <= 1:
        return np.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    fac = np.linspace(-np.pi, np.pi, M)
    w = np.zeros(M)
    for k, ak in enumerate(a):
        w += ak * np.cos(k * fac)
    return _truncate(w, trunc)


def _general_hamming(M, alpha, sym):
    return _general_cosine(M, [alpha, 1.0 - alpha], sym)


def _hamming(M, sym=True):
    return _general_hamming(M, 0.54, sym)


def _hann(M, sym=True):
    return _general_hamming(M, 0.5, sym)


def _blackman(M, sym=True):
    return _general_cosine(M, [0.42, 0.50, 0.08], sym)


def _nuttall(M, sym=True):
    return _general_cosine(M, [0.3635819, 0.4891775, 0.1365995, 0.0106411],
                           sym)


def _gaussian(M, std, sym=True):
    if M <= 1:
        return np.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    n = np.arange(M) - (M - 1) / 2
    w = np.exp(-(n ** 2) / (2 * std * std))
    return _truncate(w, trunc)


def _exponential(M, center=None, tau=1.0, sym=True):
    if sym and center is not None:
        raise ValueError("symmetric exponential window takes no center")
    if M <= 1:
        return np.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    if center is None:
        center = (M - 1) / 2
    n = np.arange(M)
    w = np.exp(-np.abs(n - center) / tau)
    return _truncate(w, trunc)


def _triang(M, sym=True):
    if M <= 1:
        return np.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    n = np.arange(1, (M + 1) // 2 + 1)
    if M % 2 == 0:
        w = (2 * n - 1.0) / M
        w = np.concatenate([w, w[::-1]])
    else:
        w = 2 * n / (M + 1.0)
        w = np.concatenate([w, w[-2::-1]])
    return _truncate(w, trunc)


def _bohman(M, sym=True):
    if M <= 1:
        return np.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    fac = np.abs(np.linspace(-1, 1, M)[1:-1])
    w = (1 - fac) * np.cos(np.pi * fac) + 1.0 / np.pi * np.sin(np.pi * fac)
    w = np.concatenate([[0], w, [0]])
    return _truncate(w, trunc)


def _cosine(M, sym=True):
    if M <= 1:
        return np.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    w = np.sin(np.pi / M * (np.arange(M) + 0.5))
    return _truncate(w, trunc)


def _tukey(M, alpha=0.5, sym=True):
    if M <= 1:
        return np.ones(max(M, 0))
    if alpha <= 0:
        return np.ones(M)
    if alpha >= 1.0:
        return _hann(M, sym)
    M, trunc = _extend(M, sym)
    n = np.arange(M)
    width = int(alpha * (M - 1) / 2.0)
    n1 = n[: width + 1]
    n2 = n[width + 1: M - width - 1]
    n3 = n[M - width - 1:]
    w1 = 0.5 * (1 + np.cos(np.pi * (-1 + 2.0 * n1 / alpha / (M - 1))))
    w2 = np.ones(n2.shape[0])
    w3 = 0.5 * (1 + np.cos(np.pi * (-2.0 / alpha + 1
                                    + 2.0 * n3 / alpha / (M - 1))))
    w = np.concatenate([w1, w2, w3])
    return _truncate(w, trunc)


def _taylor(M, nbar=4, sll=30, norm=True, sym=True):
    if M <= 1:
        return np.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    B = 10 ** (sll / 20)
    A = math.acosh(B) / np.pi
    s2 = nbar ** 2 / (A ** 2 + (nbar - 0.5) ** 2)
    ma = np.arange(1, nbar)
    Fm = np.empty(nbar - 1)
    signs = np.empty_like(ma)
    signs[::2] = 1
    signs[1::2] = -1
    m2 = ma * ma
    for mi, _ in enumerate(ma):
        numer = signs[mi] * np.prod(
            1 - m2[mi] / s2 / (A ** 2 + (ma - 0.5) ** 2))
        denom = 2 * np.prod(1 - m2[mi] / m2[:mi]) * np.prod(
            1 - m2[mi] / m2[mi + 1:])
        Fm[mi] = numer / denom

    def W(n):
        return 1 + 2 * np.dot(
            Fm, np.cos(2 * np.pi * ma[:, None] * (n - M / 2.0 + 0.5) / M))

    w = W(np.arange(M))
    if norm:
        w = w / W((M - 1) / 2)
    return _truncate(w, trunc)


_WINDOWS = {
    "hamming": _hamming,
    "hann": _hann,
    "blackman": _blackman,
    "nuttall": _nuttall,
    "gaussian": _gaussian,
    "exponential": _exponential,
    "triang": _triang,
    "bohman": _bohman,
    "cosine": _cosine,
    "tukey": _tukey,
    "taylor": _taylor,
}


def get_window(window, win_length, fftbins=True, dtype="float64"):
    """reference window.py:335 — window can be a name or (name, *params)."""
    sym = not fftbins
    if isinstance(window, (str,)):
        name, args = window, ()
    elif isinstance(window, tuple):
        name, args = window[0], window[1:]
    else:
        raise ValueError(f"invalid window spec {window!r}")
    if name not in _WINDOWS:
        raise ValueError(f"unknown window {name!r}; "
                         f"supported: {sorted(_WINDOWS)}")
    w = _WINDOWS[name](win_length, *args, sym=sym)
    return Tensor(np.asarray(w, dtype))
