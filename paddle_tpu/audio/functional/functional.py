"""Audio DSP helpers (reference audio/functional/functional.py: hz_to_mel
:24, mel_to_hz :80, mel_frequencies :125, fft_frequencies :165,
compute_fbank_matrix :188, power_to_db :261, create_dct :305).

Slaney mel scale by default (htk=False), matching the reference/librosa.
Scalar math runs in numpy; Tensor inputs go through dispatch ops.
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ...core.dispatch import op
from ...core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct"]


def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x,
                      dtype=np.float64)


def hz_to_mel(freq, htk=False):
    scalar = not isinstance(freq, Tensor)
    f = _np(freq)
    if htk:
        mels = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_sp = 200.0 / 3
        mels = f / f_sp
        min_log_hz = 1000.0
        min_log_mel = min_log_hz / f_sp
        logstep = math.log(6.4) / 27.0
        log_t = min_log_mel + np.log(f / min_log_hz + 1e-10) / logstep
        mels = np.where(f >= min_log_hz, log_t, mels)
    return float(mels) if scalar and mels.ndim == 0 else Tensor(
        mels.astype(np.float32))


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, Tensor)
    m = _np(mel)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_sp = 200.0 / 3
        f = f_sp * m
        min_log_hz = 1000.0
        min_log_mel = min_log_hz / f_sp
        logstep = math.log(6.4) / 27.0
        log_t = min_log_hz * np.exp(logstep * (m - min_log_mel))
        f = np.where(m >= min_log_mel, log_t, f)
    return float(f) if scalar and f.ndim == 0 else Tensor(
        f.astype(np.float32))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    low = hz_to_mel(float(f_min), htk)
    high = hz_to_mel(float(f_max), htk)
    mels = np.linspace(low, high, n_mels)
    return Tensor(_np(mel_to_hz(Tensor(mels.astype(np.float32)),
                                htk)).astype(dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(np.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, 1 + n_fft//2] triangular mel filterbank."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = _np(fft_frequencies(sr, n_fft))
    mel_f = _np(mel_frequencies(n_mels + 2, f_min, f_max, htk))
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2: n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights.astype(dtype))


@op("power_to_db_op")
def _power_to_db(x, ref_value=1.0, amin=1e-10, top_db=80.0):
    log_spec = 10.0 * (jnp.log10(jnp.maximum(amin, x))
                       - jnp.log10(jnp.maximum(amin, ref_value)))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec


def power_to_db(x, ref_value=1.0, amin=1e-10, top_db=80.0):
    """reference functional.py:261."""
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if top_db is not None and top_db < 0:
        raise ValueError("top_db must be non-negative")
    return _power_to_db(x, ref_value=float(ref_value), amin=float(amin),
                        top_db=None if top_db is None else float(top_db))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II basis (reference functional.py:305)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k) * 2.0
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(1.0 / (2.0 * n_mels))
    return Tensor(dct.astype(dtype))
