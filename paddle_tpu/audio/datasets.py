"""paddle.audio.datasets (reference python/paddle/audio/datasets/).

Download-free: TESS/ESC50 read a local extracted folder (the same layout
the reference's downloader produces) and emit (feature, label) pairs using
paddle.audio.features on host.
"""

from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["TESS", "ESC50", "AudioClassificationDataset"]


def _load_wav(path):
    import wave

    with wave.open(path, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        raw = np.frombuffer(w.readframes(n), np.int16)
        if w.getnchannels() > 1:
            raw = raw.reshape(-1, w.getnchannels()).mean(1)
    return raw.astype(np.float32) / 32768.0, sr


class AudioClassificationDataset(Dataset):
    """Base (reference audio/datasets/dataset.py): files + labels ->
    (waveform-or-feature, label)."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **kwargs):
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.feat_kwargs = kwargs

    def _feature(self, wav, sr):
        if self.feat_type == "raw":
            return wav
        import paddle_tpu as paddle

        from . import features

        t = paddle.to_tensor(wav[None])
        if self.feat_type == "melspectrogram":
            return features.MelSpectrogram(sr=sr, **self.feat_kwargs)(t)
        if self.feat_type == "mfcc":
            return features.MFCC(sr=sr, **self.feat_kwargs)(t)
        if self.feat_type == "logmelspectrogram":
            return features.LogMelSpectrogram(sr=sr, **self.feat_kwargs)(t)
        if self.feat_type == "spectrogram":
            return features.Spectrogram(**self.feat_kwargs)(t)
        raise ValueError(f"unknown feat_type {self.feat_type!r}")

    def __getitem__(self, idx):
        wav, sr = _load_wav(self.files[idx])
        return self._feature(wav, sr), np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


class TESS(AudioClassificationDataset):
    """Toronto emotional speech set (reference audio/datasets/tess.py):
    label = emotion from the filename suffix. Pass the extracted folder as
    ``data_file``."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_file=None, archive=None, **kwargs):
        if not data_file:
            raise ValueError("no network egress: TESS needs the local "
                             "extracted dataset folder as data_file")
        files, labels = [], []
        for root, _, names in os.walk(data_file):
            for n in sorted(names):
                if not n.lower().endswith(".wav"):
                    continue
                emo = n.rsplit("_", 1)[-1][:-4].lower()
                if emo in self.EMOTIONS:
                    files.append(os.path.join(root, n))
                    labels.append(self.EMOTIONS.index(emo))
        fold = np.arange(len(files)) % n_folds + 1
        keep = (fold != split) if mode == "train" else (fold == split)
        files = [f for f, k in zip(files, keep) if k]
        labels = [l for l, k in zip(labels, keep) if k]
        super().__init__(files, labels, feat_type=feat_type, **kwargs)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py):
    label and fold parsed from the canonical filename
    ``{fold}-{id}-{take}-{target}.wav``."""

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_file=None, **kwargs):
        if not data_file:
            raise ValueError("no network egress: ESC50 needs the local "
                             "extracted dataset folder as data_file")
        files, labels = [], []
        for root, _, names in os.walk(data_file):
            for n in sorted(names):
                if not n.lower().endswith(".wav"):
                    continue
                parts = n[:-4].split("-")
                if len(parts) != 4:
                    continue
                fold, target = int(parts[0]), int(parts[3])
                keep = (fold != split) if mode == "train" else (fold == split)
                if keep:
                    files.append(os.path.join(root, n))
                    labels.append(target)
        super().__init__(files, labels, feat_type=feat_type, **kwargs)
