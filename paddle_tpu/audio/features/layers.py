"""Audio feature layers (reference audio/features/layers.py: Spectrogram
:24, MelSpectrogram :106, LogMelSpectrogram :206, MFCC :309) built over
paddle.signal.stft + the functional mel/DCT helpers."""

from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ...ops import manipulation as M
from ...ops import math as ops_math
from .. import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length if hop_length is not None else n_fft // 4
        self.win_length = win_length if win_length is not None else n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = AF.get_window(window, self.win_length, dtype=dtype)
        self.register_buffer("fft_window", w, persistable=False)

    def forward(self, x):
        from ... import signal

        from ...fft import _complex_ok

        spec = signal.stft(x, self.n_fft, hop_length=self.hop_length,
                           win_length=self.win_length,
                           window=self.fft_window, center=self.center,
                           pad_mode=self.pad_mode)
        if _complex_ok():
            # device path: differentiable and jit-traceable
            mag = ops_math.abs(spec)
        else:
            # axon complex fallback: the spectrum lives on the host
            # (eager-only, like every complex op on this backend)
            mag = Tensor(np.abs(np.asarray(spec._data)).astype(np.float32))
        if self.power == 2.0:
            return mag * mag
        if self.power != 1.0:
            return mag.pow(self.power)
        return mag


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.n_mels = n_mels
        fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                        htk, norm, dtype)
        self.register_buffer("fbank_matrix", fbank, persistable=False)

    def forward(self, x):
        spec = self._spectrogram(x)  # [..., n_freq, n_frames]
        return ops_math.matmul(self.fbank_matrix, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                              top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, norm="ortho", dtype="float32",
                 **melkw):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(sr=sr, dtype=dtype,
                                                     **melkw)
        n_mels = self._log_melspectrogram._melspectrogram.n_mels
        assert n_mfcc <= n_mels, "n_mfcc cannot exceed n_mels"
        dct = AF.create_dct(n_mfcc, n_mels, norm, dtype)
        self.register_buffer("dct_matrix", dct, persistable=False)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)  # [..., n_mels, n_frames]
        # [n_mels, n_mfcc]^T @ [..., n_mels, n_frames]
        return ops_math.matmul(M.transpose(self.dct_matrix, [1, 0]), logmel)
