"""paddle.audio — windows, mel/DSP helpers, feature layers, WAV IO.

Reference package: python/paddle/audio/ (functional/, features/, backends/;
datasets/ are download-based and out of scope for an offline image).
"""

from . import backends, datasets, features, functional  # noqa: F401
from .backends import info, load, save  # noqa: F401

__all__ = ["functional", "features", "backends", "datasets", "load",
           "save", "info"]
