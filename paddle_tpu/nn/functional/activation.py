"""Activation functionals (reference: python/paddle/nn/functional/activation.py,
PHI kernels paddle/phi/kernels/activation_kernel.h). Pure JAX; XLA fuses these
into surrounding matmuls."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import op
from ...core.tensor import Tensor

__all__ = [
    "relu", "relu_", "relu6", "gelu", "sigmoid", "log_sigmoid", "tanh",
    "softmax", "log_softmax", "silu", "swish", "mish", "hardswish",
    "hardsigmoid", "hardtanh", "leaky_relu", "elu", "selu", "celu", "prelu",
    "rrelu", "softplus", "softsign", "softshrink", "hardshrink", "tanhshrink",
    "thresholded_relu", "maxout", "glu", "gumbel_softmax",
]

relu = op("relu")(jax.nn.relu)
sigmoid = op("sigmoid_f")(jax.nn.sigmoid)
tanh = op("tanh_f")(jnp.tanh)
log_sigmoid = op("log_sigmoid")(jax.nn.log_sigmoid)
silu = op("silu")(jax.nn.silu)
softsign = op("softsign")(jax.nn.soft_sign)
tanhshrink = op("tanhshrink")(lambda x: x - jnp.tanh(x))
mish = op("mish")(lambda x: x * jnp.tanh(jax.nn.softplus(x)))
hardswish = op("hardswish")(lambda x: x * jnp.clip(x + 3, 0, 6) / 6)


def relu_(x, name=None):
    out = relu(x)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


@op("relu6")
def _relu6(x, threshold=6.0):
    return jnp.clip(x, 0, threshold)


def relu6(x, name=None):
    return _relu6(x)


@op("gelu")
def _gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return _gelu(x, approximate=bool(approximate))


@op("softmax_f")
def _softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype)
    return _softmax(x, axis=int(axis))


@op("log_softmax_f")
def _log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype)
    return _log_softmax(x, axis=int(axis))


@op("swish")
def _swish(x):
    return jax.nn.silu(x)


def swish(x, name=None):
    return _swish(x)


@op("hardsigmoid")
def _hardsigmoid(x, slope=1 / 6, offset=0.5):
    return jnp.clip(x * slope + offset, 0.0, 1.0)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _hardsigmoid(x, slope=float(slope), offset=float(offset))


@op("hardtanh")
def _hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _hardtanh(x, min=float(min), max=float(max))


@op("leaky_relu")
def _leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky_relu(x, negative_slope=float(negative_slope))


@op("elu")
def _elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def elu(x, alpha=1.0, name=None):
    return _elu(x, alpha=float(alpha))


@op("selu")
def _selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _selu(x, scale=float(scale), alpha=float(alpha))


@op("celu")
def _celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


def celu(x, alpha=1.0, name=None):
    return _celu(x, alpha=float(alpha))


@op("prelu_op")
def _prelu(x, weight, data_format="NCHW"):
    if weight.size == 1:
        w = weight.reshape(())
    else:
        shape = [1] * x.ndim
        ch_axis = 1 if data_format == "NCHW" and x.ndim >= 2 else x.ndim - 1
        shape[ch_axis] = weight.size
        w = weight.reshape(shape)
    return jnp.where(x > 0, x, w * x)


def prelu(x, weight, data_format="NCHW", name=None):
    return _prelu(x, weight, data_format=data_format)


@op("rrelu_train")
def _rrelu(x, key, lower=0.125, upper=0.333):
    a = jax.random.uniform(key, x.shape, jnp.float32, lower, upper).astype(x.dtype)
    return jnp.where(x >= 0, x, a * x)


def rrelu(x, lower=0.125, upper=0.333, training=False, name=None):
    if not training:
        return _leaky_relu(x, negative_slope=(lower + upper) / 2)
    from ...core import rng

    return _rrelu(x, rng.next_key(), lower=float(lower), upper=float(upper))


@op("softplus")
def _softplus(x, beta=1.0, threshold=20.0):
    scaled = x * beta
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _softplus(x, beta=float(beta), threshold=float(threshold))


@op("softshrink")
def _softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def softshrink(x, threshold=0.5, name=None):
    return _softshrink(x, threshold=float(threshold))


@op("hardshrink")
def _hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def hardshrink(x, threshold=0.5, name=None):
    return _hardshrink(x, threshold=float(threshold))


@op("thresholded_relu")
def _thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return _thresholded_relu(x, threshold=float(threshold), value=float(value))


@op("maxout")
def _maxout(x, groups=1, axis=1):
    shape = list(x.shape)
    c = shape[axis]
    shape[axis : axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(shape), axis=axis + 1)


def maxout(x, groups, axis=1, name=None):
    return _maxout(x, groups=int(groups), axis=int(axis))


@op("glu_op")
def _glu(x, axis=-1):
    return jax.nn.glu(x, axis=axis)


def glu(x, axis=-1, name=None):
    return _glu(x, axis=int(axis))


@op("gumbel_softmax_op")
def _gumbel_softmax(x, key, temperature=1.0, hard=False, axis=-1):
    g = jax.random.gumbel(key, x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.put_along_axis(jnp.zeros_like(y), idx, 1.0, axis=axis,
                                    inplace=False)
        y = onehot + y - jax.lax.stop_gradient(y)
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import rng

    return _gumbel_softmax(x, rng.next_key(), temperature=float(temperature),
                           hard=bool(hard), axis=int(axis))


def _act_inplace(fn):
    """Reference exposes inplace activation variants (elu_ etc.); under XLA
    ops are functional, so inplace = rebind (same contract as Tensor.add_)."""

    def inplace(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
        x.stop_gradient = out.stop_gradient and x.stop_gradient
        return x

    inplace.__name__ = fn.__name__ + "_"
    return inplace


elu_ = _act_inplace(elu)
hardtanh_ = _act_inplace(hardtanh)
leaky_relu_ = _act_inplace(leaky_relu)
softmax_ = _act_inplace(softmax)
tanh_ = _act_inplace(tanh)
thresholded_relu_ = _act_inplace(thresholded_relu)

__all__ += ["elu_", "hardtanh_", "leaky_relu_", "softmax_", "tanh_",
            "thresholded_relu_"]
