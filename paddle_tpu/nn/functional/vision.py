"""Spatial-transform functionals.

Reference: python/paddle/nn/functional/vision.py (affine_grid :30,
grid_sample :237, temporal_shift) over the CUDA kernels
paddle/phi/kernels/gpu/affine_grid_kernel.cu / grid_sample_kernel.cu.
TPU-native: pure gather/arith forms that XLA vectorizes; bilinear
grid_sample is two fused gathers + lerp on the MXU-free VPU path.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = ["affine_grid", "grid_sample", "temporal_shift"]


def _lin(n, align_corners):
    if align_corners:
        return jnp.linspace(-1.0, 1.0, n)
    # pixel centers of a [-1, 1] box split into n cells
    step = 2.0 / n
    return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)


@op("affine_grid")
def _affine_grid(theta, out_h=1, out_w=1, align_corners=True):
    n = theta.shape[0]
    ys = _lin(out_h, align_corners)
    xs = _lin(out_w, align_corners)
    gx, gy = jnp.meshgrid(xs, ys)                       # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)           # [H, W, 3]
    # grid = base @ theta^T : [N, H, W, 2]; tiny matmul — full f32 precision
    # (coordinates feed gathers, bf16 rounding moves sample positions)
    return jnp.einsum("hwc,nkc->nhwk", base, theta.astype(jnp.float32),
                      precision=jax.lax.Precision.HIGHEST)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta: [N, 2, 3] affine matrices -> sampling grid [N, H, W, 2]."""
    if hasattr(out_shape, "tolist"):
        out_shape = out_shape.tolist()
    n, c, h, w = (int(s) for s in out_shape)
    return _affine_grid(theta, out_h=h, out_w=w,
                        align_corners=bool(align_corners))


@op("grid_sample")
def _grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                 align_corners=True):
    nb, c, h, w = x.shape
    gx = grid[..., 0].astype(jnp.float32)               # [N, Hg, Wg]
    gy = grid[..., 1].astype(jnp.float32)
    if align_corners:
        fx = (gx + 1.0) * (w - 1) / 2.0
        fy = (gy + 1.0) * (h - 1) / 2.0
    else:
        fx = ((gx + 1.0) * w - 1.0) / 2.0
        fy = ((gy + 1.0) * h - 1.0) / 2.0

    def _reflect(v, lo, hi):
        """Fold v into [lo, hi] by reflection (torch/paddle
        reflect_coordinates semantics — applied to the FLOAT coordinate so
        bilinear weights reflect too)."""
        span = hi - lo
        if span <= 0:
            return jnp.zeros_like(v)
        a = jnp.abs(v - lo)
        m = jnp.mod(a, 2.0 * span)
        return jnp.where(m >= span, 2.0 * span - m, m) + lo

    # padding transforms act on the float sample coordinates
    if padding_mode == "border":
        fx = jnp.clip(fx, 0, w - 1)
        fy = jnp.clip(fy, 0, h - 1)
    elif padding_mode == "reflection":
        if align_corners:
            fx = _reflect(fx, 0.0, float(w - 1))
            fy = _reflect(fy, 0.0, float(h - 1))
        else:
            fx = jnp.clip(_reflect(fx, -0.5, w - 0.5), 0, w - 1)
            fy = jnp.clip(_reflect(fy, -0.5, h - 0.5), 0, h - 1)

    def fetch(ix, iy):
        """x[n, :, iy, ix]; out-of-range is zero ('zeros' mode — the other
        modes already folded the coordinates in range)."""
        valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        bn = jnp.arange(nb)[:, None, None]
        vals = x[bn, :, iyc, ixc]                       # [N, Hg, Wg, C]
        vals = jnp.moveaxis(vals, -1, 1)
        return jnp.where(valid[:, None], vals, 0.0)

    if mode == "nearest":
        return fetch(jnp.round(fx).astype(jnp.int32),
                     jnp.round(fy).astype(jnp.int32)).astype(x.dtype)

    x0 = jnp.floor(fx).astype(jnp.int32)
    y0 = jnp.floor(fy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx = (fx - x0)[:, None]
    wy = (fy - y0)[:, None]
    v00 = fetch(x0, y0)
    v01 = fetch(x1, y0)
    v10 = fetch(x0, y1)
    v11 = fetch(x1, y1)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return (top * (1 - wy) + bot * wy).astype(x.dtype)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N,C,H,W] at grid [N,Hg,Wg,2] (xy in [-1,1])."""
    return _grid_sample(x, grid, mode=mode, padding_mode=padding_mode,
                        align_corners=bool(align_corners))


@op("temporal_shift")
def _temporal_shift(x, seg_num=1, shift_ratio=0.25):
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    back = jnp.concatenate(
        [xr[:, 1:, :fold], jnp.zeros_like(xr[:, :1, :fold])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, fold:2 * fold]),
         xr[:, :-1, fold:2 * fold]], axis=1)
    rest = xr[:, :, 2 * fold:]
    return jnp.concatenate([back, fwd, rest], axis=2).reshape(nt, c, h, w)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM channel shift across the segment (time) dim (reference
    vision.py temporal_shift)."""
    if data_format == "NHWC":
        from ...ops.manipulation import transpose

        x = transpose(x, [0, 3, 1, 2])
        out = _temporal_shift(x, seg_num=int(seg_num),
                              shift_ratio=float(shift_ratio))
        return transpose(out, [0, 2, 3, 1])
    return _temporal_shift(x, seg_num=int(seg_num),
                           shift_ratio=float(shift_ratio))
