"""Ulysses-style all-to-all sequence-parallel attention.

Companion to ring attention (ring_attention.py) for the long-context story
(SURVEY §5.7): the sequence is sharded over a mesh axis; an ``all_to_all``
re-shards from sequence-parallel [B, S/P, H, D] to HEAD-parallel
[B, S, H/P, D], each device runs ordinary full-sequence attention over its
head group, and a second ``all_to_all`` restores sequence sharding
(DeepSpeed-Ulysses; the reference's sep axis carries the same layout
contract, with the attention compute living out-of-tree in PaddleNLP).

Trade-off vs ring: Ulysses moves 2×(q+k+v+o)/P bytes in two bursts over ICI
and keeps attention as ONE dense kernel per device (best when heads >> P
and the flash kernel dominates); ring moves k+v per step in P-1 overlapped
hops and never materializes the full sequence (best when S/P is the memory
binding constraint). Both are reverse-differentiable by construction
(all_to_all/ppermute transpose to themselves).

Constraint: num_heads and seq_len must be divisible by the axis size (the
same constraint DeepSpeed-Ulysses carries). GQA kv heads that the axis
cannot split are broadcast to full head count up front — axis
compatibility at the cost of the GQA bandwidth saving.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import PartitionSpec as P

from ._seq_parallel import (
    place_seq_sharded,
    resolve_sp_mesh,
    single_device_fallback,
)

__all__ = ["sep_all_to_all_attention"]


def _ulysses_local(q, k, v, axis_name, causal, scale):
    """Shard body: q/k/v [B, S_loc, H, D] (seq-sharded)."""
    from .flash_attention import _sdpa_ref

    # seq-parallel -> head-parallel: split heads over the axis, gather seq
    qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)              # [B, S, H/P, D]
    kh = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    # full-sequence dense attention on the head slice — the ONE sdpa
    # implementation (GQA broadcast, causal mask, f32 softmax) shared with
    # the single-device path
    out = _sdpa_ref.raw_fn(qh, kh, vh, causal=causal,
                           scale=scale).astype(q.dtype)
    # head-parallel -> seq-parallel: split seq, gather heads back
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)            # [B, S_loc, H, D]


from ...core.dispatch import op as _op


@_op("sep_all_to_all_attention")
def _ulysses_op(q, k, v, mesh=None, axis="sep", causal=False, scale=1.0):
    spec = P(None, axis, None, None)
    return jax.shard_map(
        lambda q_, k_, v_: _ulysses_local(q_, k_, v_, axis_name=axis,
                                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names={axis}, check_vma=False)(q, k, v)


def sep_all_to_all_attention(query, key, value, mesh=None, axis="sep",
                             causal=False, scale=None):
    """Sequence-parallel attention via head/sequence all_to_all re-shard:
    [B, S, H, D] with S sharded over ``axis``. Falls back to single-device
    flash/SDPA when no mesh axis is available (so models can call it
    unconditionally), mirroring :func:`ring_flash_attention`'s contract.
    """
    mesh = resolve_sp_mesh(mesh, axis)
    if mesh is None:
        return single_device_fallback(query, key, value, causal, scale)
    n = mesh.shape[axis]
    seq = query.shape[1]
    h = query.shape[2]
    kvh = key.shape[2]
    if h % n or seq % n:
        raise ValueError(
            f"sep_all_to_all_attention needs num_heads AND seq_len "
            f"divisible by the '{axis}' axis size: heads={h}, seq={seq}, "
            f"axis={n}. Use ring_flash_attention for head counts the axis "
            "cannot split.")
    if kvh % n:
        # GQA with kv heads the axis cannot split: broadcast kv heads up
        # front (DeepSpeed-Ulysses does the same; trades GQA bandwidth for
        # axis compatibility) — but only to the SMALLEST multiple the axis
        # can split that still groups q heads evenly, not all the way to h
        # (kv all_to_all bytes scale with the broadcast factor).
        # repeat_interleave keeps the q-head grouping the dense GQA
        # reference uses.
        if h % kvh:
            raise ValueError(
                f"GQA head grouping broken: heads={h} not a multiple of "
                f"kv_heads={kvh}")
        rep = n // math.gcd(kvh, n)
        if h % (kvh * rep):
            rep = h // kvh  # full broadcast keeps grouping valid always
        from ...ops.manipulation import repeat_interleave

        key = repeat_interleave(key, rep, axis=2)
        value = repeat_interleave(value, rep, axis=2)
    s = float(scale if scale is not None
              else 1.0 / math.sqrt(query.shape[-1]))
    place = lambda t: place_seq_sharded(t, mesh, axis)
    return _ulysses_op(place(query), place(key), place(value), mesh=mesh,
                       axis=axis, causal=bool(causal), scale=s)
