"""Ring attention — context parallelism over a mesh axis.

Beyond-reference TPU extension (SURVEY §5.7: the reference's long-context
story stops at Megatron-SP + sep-axis sharding; ring attention is the natural
ICI idiom). The sequence is sharded over a mesh axis; each step every device
computes attention of its local Q block against the K/V block it currently
holds, accumulates with the online-softmax (flash) recurrence, and rotates
K/V one hop around the ring with ``lax.ppermute`` — seq_len/N memory per
device, N steps, compute/communication overlapped by XLA's scheduler.

Autodiff: the loop is a ``lax.scan`` (reverse-differentiable); ppermute
transposes to the reverse rotation, so ``jax.grad`` of the ring forward IS
the ring backward.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_flash_attention"]


def _ring_local(q, k, v, axis_name, causal, scale):
    """Local shard body: q/k/v [B, S_loc, H, D] (this device's seq chunk)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B, H, Sq, D]
    sq = qt.shape[2]

    perm = [(j, (j + 1) % n) for j in range(n)]
    m0 = jnp.full(qt.shape[:3], -jnp.inf, jnp.float32)
    l0 = jnp.zeros(qt.shape[:3], jnp.float32)
    acc0 = jnp.zeros(qt.shape, jnp.float32)

    def step(carry, i):
        m, l, acc, kc, vc = carry
        src = (idx - i) % n  # rank that produced the chunk we now hold
        kt = jnp.swapaxes(kc, 1, 2).astype(jnp.float32)
        vt = jnp.swapaxes(vc, 1, 2).astype(jnp.float32)
        # GQA: the ring rotates the NARROW kv chunks (that is the memory/ICI
        # saving GQA exists for); heads broadcast only here, at use
        if kt.shape[1] != qt.shape[1]:
            rep = qt.shape[1] // kt.shape[1]
            kt = jnp.repeat(kt, rep, axis=1)
            vt = jnp.repeat(vt, rep, axis=1)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
        if causal:
            sk = s.shape[-1]
            tril = jnp.tril(jnp.ones((sq, sk), bool))
            chunk_mask = jnp.where(src > idx, False,
                                   jnp.where(src == idx, tril, True))
            s = jnp.where(chunk_mask, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(-1))
        # fully-masked rows keep m=-inf; guard the exp
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(jnp.where(jnp.isneginf(s), -jnp.inf,
                              s - m_safe[..., None]))
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l = l * alpha + p.sum(-1)
        acc = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vt)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (m_new, l, acc, kc, vc), None

    (m, l, acc, _, _), _ = jax.lax.scan(step, (m0, l0, acc0, k, v),
                                        jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


from ...core.dispatch import op as _op


@_op("ring_flash_attention")
def _ring_op(q, k, v, mesh=None, axis="sep", causal=False, scale=1.0):
    spec = P(None, axis, None, None)
    return jax.shard_map(
        lambda q_, k_, v_: _ring_local(q_, k_, v_, axis_name=axis,
                                       causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names={axis}, check_vma=False)(q, k, v)


def ring_flash_attention(query, key, value, mesh=None, axis="sep",
                         causal=False, scale=None):
    """Context-parallel attention: [B, S, H, D] with S sharded over
    ``axis``. Falls back to single-device flash/SDPA when no mesh axis is
    available (so models can call it unconditionally)."""
    from ._seq_parallel import (
        place_seq_sharded,
        resolve_sp_mesh,
        single_device_fallback,
    )

    mesh = resolve_sp_mesh(mesh, axis)
    if mesh is None:
        return single_device_fallback(query, key, value, causal, scale)
    s = float(scale if scale is not None
              else 1.0 / math.sqrt(query.shape[-1]))
    place = lambda t: place_seq_sharded(t, mesh, axis)
    # dispatch op: jit-cached, tape-recorded (grads ring backward via the
    # ppermute transpose inside jax.vjp)
    return _ring_op(place(query), place(key), place(value), mesh=mesh,
                    axis=axis, causal=bool(causal), scale=s)
