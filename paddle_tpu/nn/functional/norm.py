"""Normalization functionals.

Reference: python/paddle/nn/functional/norm.py, PHI kernels
layer_norm_kernel.h / batch_norm_kernel.h. Stats are computed in float32
regardless of input dtype (matches the reference's AMP-safe norm kernels),
then cast back — important for bf16 training.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import op
from ...core.tensor import Tensor

__all__ = [
    "layer_norm", "batch_norm", "instance_norm", "group_norm", "local_response_norm",
    "normalize", "rms_norm",
]


@op("layer_norm_op")
def _layer_norm(x, weight=None, bias=None, epsilon=1e-5, begin_norm_axis=-1):
    axes = tuple(range(begin_norm_axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    begin = x.ndim - len(normalized_shape)
    return _layer_norm(x, weight, bias, epsilon=float(epsilon),
                       begin_norm_axis=int(begin))


@op("rms_norm_op")
def _rms_norm(x, weight=None, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    return out.astype(x.dtype)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """TPU-native extension (the reference has fused rms_norm in
    paddle/phi/kernels/gpu/rms_norm_kernel.cu via incubate)."""
    return _rms_norm(x, weight, epsilon=float(epsilon))


@op("batch_norm_infer")
def _batch_norm_infer(x, mean, var, weight=None, bias=None, epsilon=1e-5,
                      channel_axis=1):
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    xf = x.astype(jnp.float32)
    out = (xf - mean.reshape(shape)) * jax.lax.rsqrt(
        var.reshape(shape).astype(jnp.float32) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype)


@op("batch_norm_train")
def _batch_norm_train(x, weight=None, bias=None, epsilon=1e-5, channel_axis=1):
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    out = (xf - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype), mean, var


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    channel_axis = 1 if data_format.startswith("NC") or x.ndim <= 2 else x.ndim - 1
    if x.ndim <= 2:
        channel_axis = x.ndim - 1
    if use_global_stats is None:
        use_global_stats = not training
    if use_global_stats:
        return _batch_norm_infer(x, running_mean, running_var, weight, bias,
                                 epsilon=float(epsilon), channel_axis=channel_axis)
    out, mean, var = _batch_norm_train(x, weight, bias, epsilon=float(epsilon),
                                       channel_axis=channel_axis)
    if running_mean is not None and not isinstance(mean._data, jax.core.Tracer):
        # Running-stat update is a host-side side effect; under @to_static
        # tracing it is skipped (stats are frozen at trace time — use
        # use_global_stats or eval mode for compiled BN, as with the
        # reference's static-graph BN).
        m = float(momentum)
        running_mean._rebind(
            (running_mean._data * m + mean._data * (1 - m)).astype(
                running_mean._data.dtype))
        running_var._rebind(
            (running_var._data * m + var._data * (1 - m)).astype(
                running_var._data.dtype))
    return out


@op("instance_norm_op")
def _instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = out + bias.reshape(shape)
    return out.astype(x.dtype)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    return _instance_norm(x, weight, bias, epsilon=float(eps))


@op("group_norm_op")
def _group_norm(x, weight=None, bias=None, epsilon=1e-5, num_groups=1,
                channel_axis=1):
    n = x.shape[0]
    c = x.shape[channel_axis]
    g = num_groups
    xf = x.astype(jnp.float32)
    if channel_axis == 1:
        grouped = xf.reshape(n, g, c // g, *x.shape[2:])
        axes = tuple(range(2, grouped.ndim))
    else:
        grouped = xf.reshape(*x.shape[:-1], g, c // g)
        axes = tuple(range(1, grouped.ndim - 2)) + (grouped.ndim - 1,)
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.var(grouped, axis=axes, keepdims=True)
    out = ((grouped - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1] * x.ndim
    shape[channel_axis] = c
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    return _group_norm(x, weight, bias, epsilon=float(epsilon),
                       num_groups=int(num_groups), channel_axis=channel_axis)


@op("local_response_norm_op")
def _lrn(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[1]
    pads = [(0, 0)] * x.ndim
    pads[1] = (half, size - half - 1)
    padded = jnp.pad(sq, pads)
    window = [1] * x.ndim
    window[1] = size
    summed = jax.lax.reduce_window(padded, np.array(0, x.dtype), jax.lax.add,
                                   tuple(window), (1,) * x.ndim, "VALID")
    div = jnp.power(k + alpha * summed, beta)
    return x / div


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return _lrn(x, size=int(size), alpha=float(alpha), beta=float(beta),
                k=float(k))


@op("normalize_op")
def _normalize(x, p=2.0, axis=1, epsilon=1e-12):
    norm = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, epsilon)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _normalize(x, p=float(p), axis=int(axis), epsilon=float(epsilon))
