"""Shared plumbing for the sequence-parallel attention entry points
(ring_attention.py, ulysses_attention.py): mesh resolution from the fleet
singleton, the in-place sequence-sharded placement, and the scale-aware
single-device fallback contract.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor


def resolve_sp_mesh(mesh, axis):
    """The mesh to run on, or None when the axis is unavailable (callers
    then take the single-device fallback)."""
    if mesh is None:
        from ...distributed.fleet.fleet import fleet_singleton

        try:
            mesh = fleet_singleton.get_hybrid_communicate_group().mesh
        except Exception:
            mesh = None
    if mesh is None or axis not in getattr(mesh, "shape", {}) \
            or mesh.shape[axis] <= 1:
        return None
    return mesh


def place_seq_sharded(t, mesh, axis):
    """Re-layout IN PLACE (same value, sharded over the sequence axis) so
    the autograd tape identity is preserved — a wrapped copy would receive
    the leaf gradients instead of the caller's tensor."""
    if isinstance(t, Tensor) and not isinstance(t._data, jax.core.Tracer):
        sharding = NamedSharding(mesh, P(None, axis, None, None))
        t._data = jax.device_put(t._data, sharding)
    return t


def single_device_fallback(query, key, value, causal, scale):
    """No mesh axis: run ordinary attention with the SAME scale semantics
    the sharded path would use (a custom scale must not silently revert to
    1/sqrt(d) just because the deployment is single-device)."""
    from .flash_attention import _sdpa_ref, scaled_dot_product_attention

    if scale is None:
        # default scale: keep the Pallas-capable fast path
        return scaled_dot_product_attention(query, key, value,
                                            is_causal=causal)
    return _sdpa_ref(query, key, value, causal=bool(causal),
                     scale=float(scale))
