"""paddle.nn.functional namespace (reference: python/paddle/nn/functional/)."""

from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .flash_attention import (  # noqa: F401
    flash_attention, flash_attn_unpadded, fused_rope_attention,
    fused_rope_attention_enabled, scaled_dot_product_attention, sdp_kernel,
)
from . import flash_attention as flash_attention_mod  # noqa: F401
from .ring_attention import ring_flash_attention  # noqa: F401
from .ulysses_attention import sep_all_to_all_attention  # noqa: F401

from ...ops.manipulation import gather, gather_nd, scatter, scatter_nd_add  # noqa: F401
from ...ops.creation import one_hot  # noqa: F401
