"""Pooling functionals on lax.reduce_window.

Reference: python/paddle/nn/functional/pooling.py, PHI pool kernels
(paddle/phi/kernels/pool_kernel.h). NCHW layout.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else [v[0]] * n))
    return (int(v),) * n


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    p = _tup(padding, n)
    return tuple((x, x) for x in p)


@op("max_pool_nd")
def _max_pool(x, ksize=(2, 2), stride=(2, 2), padding=((0, 0), (0, 0)),
              ceil_mode=False):
    nd = len(ksize)
    window = (1, 1) + tuple(ksize)
    strides = (1, 1) + tuple(stride)
    if isinstance(padding, str):
        pads = padding
    else:
        pads = ((0, 0), (0, 0)) + tuple(padding)
        if ceil_mode:
            pads = ((0, 0), (0, 0)) + tuple(
                (lo, hi + s - 1) for (lo, hi), s in zip(padding, stride)
            )
    if jnp.issubdtype(x.dtype, jnp.floating):
        init = np.array(-np.inf, x.dtype)
    else:
        init = np.array(np.iinfo(x.dtype).min, x.dtype)
    return jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)


@op("avg_pool_nd")
def _avg_pool(x, ksize=(2, 2), stride=(2, 2), padding=((0, 0), (0, 0)),
              exclusive=True, ceil_mode=False):
    window = (1, 1) + tuple(ksize)
    strides = (1, 1) + tuple(stride)
    if isinstance(padding, str):
        pads = padding
    else:
        pads = ((0, 0), (0, 0)) + tuple(padding)
    summed = jax.lax.reduce_window(x, np.array(0, x.dtype), jax.lax.add,
                                   window, strides, pads)
    if exclusive and not isinstance(padding, str):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, np.array(0, x.dtype), jax.lax.add,
                                       window, strides, pads)
        return summed / counts
    return summed / float(np.prod(ksize))


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    out = _max_pool(x, ksize=ks, stride=st, padding=_pads(padding, 2),
                    ceil_mode=bool(ceil_mode))
    if return_mask:
        from ...ops.manipulation import argmax

        return out, None  # mask indices unsupported (reference: pool w/ mask)
    return out


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    return _max_pool(x, ksize=ks, stride=st, padding=_pads(padding, 1),
                     ceil_mode=bool(ceil_mode))


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    return _max_pool(x, ksize=ks, stride=st, padding=_pads(padding, 3),
                     ceil_mode=bool(ceil_mode))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    return _avg_pool(x, ksize=ks, stride=st, padding=_pads(padding, 1),
                     exclusive=bool(exclusive), ceil_mode=bool(ceil_mode))


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    return _avg_pool(x, ksize=ks, stride=st, padding=_pads(padding, 2),
                     exclusive=bool(exclusive), ceil_mode=bool(ceil_mode))


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    return _avg_pool(x, ksize=ks, stride=st, padding=_pads(padding, 3),
                     exclusive=bool(exclusive), ceil_mode=bool(ceil_mode))


@op("adaptive_avg_pool_nd")
def _adaptive_avg_pool(x, out_size=(1, 1)):
    nd = len(out_size)
    spatial = x.shape[2:]
    # even split windows (same as reference adaptive pooling formula)
    out = x
    for i in range(nd):
        in_len = spatial[i]
        o = out_size[i]
        if in_len % o == 0:
            k = in_len // o
            window = [1] * out.ndim
            window[2 + i] = k
            strides = [1] * out.ndim
            strides[2 + i] = k
            out = jax.lax.reduce_window(out, np.array(0, x.dtype), jax.lax.add,
                                        tuple(window), tuple(strides), "VALID") / k
        else:
            starts = (np.arange(o) * in_len) // o
            ends = ((np.arange(o) + 1) * in_len + o - 1) // o
            pieces = [
                jnp.mean(
                    jax.lax.slice_in_dim(out, int(s), int(e), axis=2 + i),
                    axis=2 + i, keepdims=True)
                for s, e in zip(starts, ends)
            ]
            out = jnp.concatenate(pieces, axis=2 + i)
    return out


@op("adaptive_max_pool_nd")
def _adaptive_max_pool(x, out_size=(1, 1)):
    nd = len(out_size)
    spatial = x.shape[2:]
    out = x
    for i in range(nd):
        in_len = spatial[i]
        o = out_size[i]
        if in_len % o == 0:
            k = in_len // o
            window = [1] * out.ndim
            window[2 + i] = k
            strides = [1] * out.ndim
            strides[2 + i] = k
            out = jax.lax.reduce_window(
                out, np.array(-np.inf, x.dtype), jax.lax.max,
                tuple(window), tuple(strides), "VALID")
        else:
            starts = (np.arange(o) * in_len) // o
            ends = ((np.arange(o) + 1) * in_len + o - 1) // o
            pieces = [
                jnp.max(jax.lax.slice_in_dim(out, int(s), int(e), axis=2 + i),
                        axis=2 + i, keepdims=True)
                for s, e in zip(starts, ends)
            ]
            out = jnp.concatenate(pieces, axis=2 + i)
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_avg_pool(x, out_size=_tup(output_size, 1))


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_avg_pool(x, out_size=_tup(output_size, 2))


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_avg_pool(x, out_size=_tup(output_size, 3))


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, out_size=_tup(output_size, 1))


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, out_size=_tup(output_size, 2))


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, out_size=_tup(output_size, 3))
