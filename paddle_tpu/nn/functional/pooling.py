"""Pooling functionals on lax.reduce_window.

Reference: python/paddle/nn/functional/pooling.py, PHI pool kernels
(paddle/phi/kernels/pool_kernel.h). NCHW layout.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d", "max_unpool1d", "max_unpool2d", "max_unpool3d",
]


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else [v[0]] * n))
    return (int(v),) * n


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    p = _tup(padding, n)
    return tuple((x, x) for x in p)


@op("max_pool_nd")
def _max_pool(x, ksize=(2, 2), stride=(2, 2), padding=((0, 0), (0, 0)),
              ceil_mode=False):
    nd = len(ksize)
    window = (1, 1) + tuple(ksize)
    strides = (1, 1) + tuple(stride)
    if isinstance(padding, str):
        pads = padding
    else:
        pads = ((0, 0), (0, 0)) + tuple(padding)
        if ceil_mode:
            pads = ((0, 0), (0, 0)) + tuple(
                (lo, hi + s - 1) for (lo, hi), s in zip(padding, stride)
            )
    if jnp.issubdtype(x.dtype, jnp.floating):
        init = np.array(-np.inf, x.dtype)
    else:
        init = np.array(np.iinfo(x.dtype).min, x.dtype)
    return jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)


@op("avg_pool_nd")
def _avg_pool(x, ksize=(2, 2), stride=(2, 2), padding=((0, 0), (0, 0)),
              exclusive=True, ceil_mode=False):
    window = (1, 1) + tuple(ksize)
    strides = (1, 1) + tuple(stride)
    if isinstance(padding, str):
        pads = padding
    else:
        pads = ((0, 0), (0, 0)) + tuple(padding)
    summed = jax.lax.reduce_window(x, np.array(0, x.dtype), jax.lax.add,
                                   window, strides, pads)
    if exclusive and not isinstance(padding, str):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, np.array(0, x.dtype), jax.lax.add,
                                       window, strides, pads)
        return summed / counts
    return summed / float(np.prod(ksize))


@op("max_pool_nd_with_index", differentiable=False)
def _max_pool_index(x, ksize=(2, 2), stride=(2, 2), padding=((0, 0), (0, 0)),
                    ceil_mode=False):
    """Argmax mask for max-pool: flat index into the (padded-free) spatial
    plane per output site — the reference's mask format
    (paddle/phi/kernels/funcs/pooling.h MaxPool*WithIndex)."""
    nd = len(ksize)
    if ceil_mode:
        # same output-size extension _max_pool applies
        padding = tuple((lo, hi + s - 1)
                        for (lo, hi), s in zip(padding, stride))
    spatial = x.shape[2:]
    patches = jax.lax.conv_general_dilated_patches(
        x.astype(jnp.float32), filter_shape=tuple(ksize),
        window_strides=tuple(stride), padding=tuple(padding),
        precision=jax.lax.Precision.DEFAULT)
    # [N, C*prod(k), *out_spatial] with channel-major ordering
    n, c = x.shape[0], x.shape[1]
    k = int(np.prod(ksize))
    out_sp = patches.shape[2:]
    # set padded positions to -inf so argmax never selects them: rebuild the
    # same patches from an all-ones input to detect padding
    ones = jnp.ones_like(x, jnp.float32)
    valid = jax.lax.conv_general_dilated_patches(
        ones, filter_shape=tuple(ksize), window_strides=tuple(stride),
        padding=tuple(padding))
    pv = patches.reshape(n, c, k, *out_sp)
    vv = valid.reshape(n, c, k, *out_sp) > 0
    pv = jnp.where(vv, pv, -jnp.inf)
    kidx = jnp.argmax(pv, axis=2)                       # [N, C, *out_sp]
    # decompose k index into per-dim offsets, then to input coordinates
    flat = jnp.zeros_like(kidx)
    rem = kidx
    for d in range(nd - 1, -1, -1):
        off = rem % ksize[d]
        rem = rem // ksize[d]
        grid = jnp.arange(out_sp[d]) * stride[d] - padding[d][0]
        shape = [1] * (2 + nd)
        shape[2 + d] = out_sp[d]
        coord = off + grid.reshape(shape)
        flat = flat + coord * int(np.prod(spatial[d + 1:]))
    return flat.astype(jnp.int32)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    out = _max_pool(x, ksize=ks, stride=st, padding=_pads(padding, 2),
                    ceil_mode=bool(ceil_mode))
    if return_mask:
        mask = _max_pool_index(x, ksize=ks, stride=st,
                               padding=_pads(padding, 2),
                               ceil_mode=bool(ceil_mode))
        return out, mask
    return out


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    out = _max_pool(x, ksize=ks, stride=st, padding=_pads(padding, 1),
                    ceil_mode=bool(ceil_mode))
    if return_mask:
        mask = _max_pool_index(x, ksize=ks, stride=st,
                               padding=_pads(padding, 1),
                               ceil_mode=bool(ceil_mode))
        return out, mask
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    out = _max_pool(x, ksize=ks, stride=st, padding=_pads(padding, 3),
                    ceil_mode=bool(ceil_mode))
    if return_mask:
        mask = _max_pool_index(x, ksize=ks, stride=st,
                               padding=_pads(padding, 3),
                               ceil_mode=bool(ceil_mode))
        return out, mask
    return out


@op("max_unpool_nd")
def _max_unpool(x, indices, out_spatial=()):
    n, c = x.shape[0], x.shape[1]
    hw = int(np.prod(out_spatial))
    flat = jnp.zeros((n, c, hw), x.dtype)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    bn = jnp.arange(n)[:, None, None]
    bc = jnp.arange(c)[None, :, None]
    flat = flat.at[bn, bc, idx].set(vals)
    return flat.reshape((n, c) + tuple(out_spatial))


def _unpool_out_spatial(in_sp, ks, st, pad, output_size):
    if output_size is not None:
        sp = tuple(int(s) for s in output_size[-len(in_sp):])
        return sp
    return tuple((i - 1) * s - 2 * p[0] + k
                 for i, k, s, p in zip(in_sp, ks, st, pad))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Inverse of max_pool1d(return_mask=True) (reference
    nn/functional/pooling.py max_unpool1d): scatters pooled values back to
    their argmax positions."""
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    sp = _unpool_out_spatial(x.shape[2:], ks, st, _pads(padding, 1),
                             output_size)
    return _max_unpool(x, indices, out_spatial=sp)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    sp = _unpool_out_spatial(x.shape[2:], ks, st, _pads(padding, 2),
                             output_size)
    return _max_unpool(x, indices, out_spatial=sp)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    sp = _unpool_out_spatial(x.shape[2:], ks, st, _pads(padding, 3),
                             output_size)
    return _max_unpool(x, indices, out_spatial=sp)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    ks = _tup(kernel_size, 1)
    st = _tup(stride if stride is not None else kernel_size, 1)
    return _avg_pool(x, ksize=ks, stride=st, padding=_pads(padding, 1),
                     exclusive=bool(exclusive), ceil_mode=bool(ceil_mode))


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    ks = _tup(kernel_size, 2)
    st = _tup(stride if stride is not None else kernel_size, 2)
    return _avg_pool(x, ksize=ks, stride=st, padding=_pads(padding, 2),
                     exclusive=bool(exclusive), ceil_mode=bool(ceil_mode))


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    ks = _tup(kernel_size, 3)
    st = _tup(stride if stride is not None else kernel_size, 3)
    return _avg_pool(x, ksize=ks, stride=st, padding=_pads(padding, 3),
                     exclusive=bool(exclusive), ceil_mode=bool(ceil_mode))


@op("adaptive_avg_pool_nd")
def _adaptive_avg_pool(x, out_size=(1, 1)):
    nd = len(out_size)
    spatial = x.shape[2:]
    # even split windows (same as reference adaptive pooling formula)
    out = x
    for i in range(nd):
        in_len = spatial[i]
        o = out_size[i]
        if in_len % o == 0:
            k = in_len // o
            window = [1] * out.ndim
            window[2 + i] = k
            strides = [1] * out.ndim
            strides[2 + i] = k
            out = jax.lax.reduce_window(out, np.array(0, x.dtype), jax.lax.add,
                                        tuple(window), tuple(strides), "VALID") / k
        else:
            starts = (np.arange(o) * in_len) // o
            ends = ((np.arange(o) + 1) * in_len + o - 1) // o
            pieces = [
                jnp.mean(
                    jax.lax.slice_in_dim(out, int(s), int(e), axis=2 + i),
                    axis=2 + i, keepdims=True)
                for s, e in zip(starts, ends)
            ]
            out = jnp.concatenate(pieces, axis=2 + i)
    return out


@op("adaptive_max_pool_nd")
def _adaptive_max_pool(x, out_size=(1, 1)):
    nd = len(out_size)
    spatial = x.shape[2:]
    out = x
    for i in range(nd):
        in_len = spatial[i]
        o = out_size[i]
        if in_len % o == 0:
            k = in_len // o
            window = [1] * out.ndim
            window[2 + i] = k
            strides = [1] * out.ndim
            strides[2 + i] = k
            out = jax.lax.reduce_window(
                out, np.array(-np.inf, x.dtype), jax.lax.max,
                tuple(window), tuple(strides), "VALID")
        else:
            starts = (np.arange(o) * in_len) // o
            ends = ((np.arange(o) + 1) * in_len + o - 1) // o
            pieces = [
                jnp.max(jax.lax.slice_in_dim(out, int(s), int(e), axis=2 + i),
                        axis=2 + i, keepdims=True)
                for s, e in zip(starts, ends)
            ]
            out = jnp.concatenate(pieces, axis=2 + i)
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_avg_pool(x, out_size=_tup(output_size, 1))


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_avg_pool(x, out_size=_tup(output_size, 2))


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_avg_pool(x, out_size=_tup(output_size, 3))


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, out_size=_tup(output_size, 1))


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, out_size=_tup(output_size, 2))


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool(x, out_size=_tup(output_size, 3))
