"""Convolution functionals on lax.conv_general_dilated.

Reference: python/paddle/nn/functional/conv.py (conv2d at :549), PHI kernels
paddle/phi/kernels/conv_kernel.h. Paddle layouts (NCHW default, OIHW weights)
are expressed via dimension_numbers; XLA lowers to MXU-tiled convs on TPU.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import op

__all__ = [
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose",
]


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 2 * n:  # per-side pairs flattened
            return tuple(v)
        return tuple(int(v[0]) for _ in range(n))
    return (int(v),) * n


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and len(padding) and \
            isinstance(padding[0], (list, tuple)):
        # [[0,0],[0,0],[pt,pb],[pl,pr]] paddle style incl. batch/channel dims
        sp = [tuple(p) for p in padding[-n:]]
        return tuple(sp)
    p = _tup(padding, n)
    if len(p) == 2 * n:
        return tuple((int(p[2 * i]), int(p[2 * i + 1])) for i in range(n))
    return tuple((int(x), int(x)) for x in p)


def _dn(ndim, channel_last, transpose=False):
    if ndim == 3:
        lhs = "NWC" if channel_last else "NCW"
        out = lhs
        rhs = "WIO" if transpose else "OIW"
    elif ndim == 4:
        lhs = "NHWC" if channel_last else "NCHW"
        out = lhs
        rhs = "HWIO" if transpose else "OIHW"
    else:
        lhs = "NDHWC" if channel_last else "NCDHW"
        out = lhs
        rhs = "DHWIO" if transpose else "OIDHW"
    return (lhs, rhs, out)


@op("conv_nd")
def _conv(x, weight, bias=None, stride=(1,), padding="VALID", dilation=(1,),
          groups=1, channel_last=False):
    n = x.ndim
    dn = _dn(n, channel_last)
    out = jax.lax.conv_general_dilated(
        x, weight,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None,
    )
    if bias is not None:
        shape = [1] * n
        shape[1 if not channel_last else n - 1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


@op("conv_nd_transpose")
def _conv_transpose(x, weight, bias=None, stride=(1,), padding=((0, 0),),
                    output_padding=(0,), dilation=(1,), groups=1,
                    channel_last=False):
    # paddle/torch-style transposed conv: gradient of conv w.r.t. input.
    # weight layout [in, out/groups, *k] (paddle conv_transpose convention)
    nd = x.ndim - 2
    kernel = weight
    # lax.conv_transpose wants IO... layouts; use conv_general_dilated with
    # lhs_dilation (fractional stride) which is the canonical XLA lowering.
    k_spatial = kernel.shape[2:]
    pads = []
    for i in range(nd):
        k_eff = (k_spatial[i] - 1) * dilation[i] + 1
        pt, pb = padding[i]
        lo = k_eff - 1 - pt
        hi = k_eff - 1 - pb + output_padding[i]
        pads.append((lo, hi))
    # flip spatial dims + swap I/O for the transposed kernel
    flip_axes = tuple(range(2, 2 + nd))
    w = jnp.flip(kernel, flip_axes)
    # [in, out/g, *k] -> groups: reshape to [g, in/g, out/g, *k] -> [g*out/g, in/g, *k]
    cin = w.shape[0]
    og = w.shape[1]
    w = w.reshape(groups, cin // groups, og, *k_spatial)
    w = jnp.swapaxes(w, 1, 2).reshape(groups * og, cin // groups, *k_spatial)
    dn = _dn(x.ndim, channel_last)
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        shape = [1] * x.ndim
        shape[1 if not channel_last else x.ndim - 1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


def _conv_fwd(x, weight, bias, stride, padding, dilation, groups, data_format, nd):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    return _conv(
        x, weight, bias,
        stride=_tup(stride, nd),
        padding=_padding(padding, nd),
        dilation=_tup(dilation, nd),
        groups=int(groups),
        channel_last=channel_last,
    )


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_fwd(x, weight, bias, stride, padding, dilation, groups,
                     "NWC" if data_format == "NLC" else "NCW", 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_fwd(x, weight, bias, stride, padding, dilation, groups,
                     data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_fwd(x, weight, bias, stride, padding, dilation, groups,
                     data_format, 3)


def _conv_transpose_fwd(x, weight, bias, stride, padding, output_padding,
                        dilation, groups, data_format, nd):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    pads = _padding(padding, nd)
    if isinstance(pads, str):
        assert pads == "VALID" or pads == "SAME", pads
        if pads == "VALID":
            pads = tuple((0, 0) for _ in range(nd))
        else:
            k = weight.shape[2:]
            pads = tuple((int(ki // 2), int(ki // 2)) for ki in k)
    return _conv_transpose(
        x, weight, bias,
        stride=_tup(stride, nd),
        padding=pads,
        output_padding=_tup(output_padding, nd),
        dilation=_tup(dilation, nd),
        groups=int(groups),
        channel_last=channel_last,
    )


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose_fwd(x, weight, bias, stride, padding, output_padding,
                               dilation, groups,
                               "NWC" if data_format == "NLC" else "NCW", 1)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose_fwd(x, weight, bias, stride, padding, output_padding,
                               dilation, groups, data_format, 2)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose_fwd(x, weight, bias, stride, padding, output_padding,
                               dilation, groups, data_format, 3)
