"""Common functionals: linear, dropout, embedding, padding, interpolate.

Reference: python/paddle/nn/functional/common.py + input.py (embedding).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import rng
from ...core.dispatch import op
from ...core.tensor import Tensor
from ...ops.manipulation import pad as _pad_nd  # noqa: F401  (re-export as F.pad)

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "embedding",
    "embedding_bag",
    "one_hot", "pad", "interpolate", "upsample", "bilinear", "cosine_similarity",
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "unfold", "fold",
    "label_smooth", "zeropad2d",
]

pad = _pad_nd


@op("linear_op")
def _linear(x, weight, bias=None):
    # paddle stores Linear weight as [in, out] (python/paddle/nn/layer/common.py)
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


def linear(x, weight, bias=None, name=None):
    return _linear(x, weight, bias)


@op("dropout_op")
def _dropout(x, key, p=0.5, mode="upscale_in_train"):
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            from ...ops.math import scale as scale_op

            return scale_op(x, scale=1.0 - p)
        return x
    if axis is not None:
        return _dropout_axis(x, rng.next_key(), p=float(p),
                             axis=tuple(np.atleast_1d(axis).tolist()), mode=mode)
    return _dropout(x, rng.next_key(), p=float(p), mode=mode)


@op("dropout_axis")
def _dropout_axis(x, key, p=0.5, axis=(0,), mode="upscale_in_train"):
    keep = 1.0 - p
    mask_shape = tuple(x.shape[i] if i in axis else 1 for i in range(x.ndim))
    mask = jax.random.bernoulli(key, keep, mask_shape)
    if mode == "upscale_in_train":
        return (jnp.where(mask, x / keep, 0.0)).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return _dropout_axis(x, rng.next_key(), p=float(p), axis=ax)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    if not training or p == 0.0:
        return x
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return _dropout_axis(x, rng.next_key(), p=float(p), axis=ax)


@op("alpha_dropout_op")
def _alpha_dropout(x, key, p=0.5):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    a = (keep + alpha_p**2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    return _alpha_dropout(x, rng.next_key(), p=float(p))


@op("embedding_op")
def _embedding(x, weight, padding_idx=None, sparse=False):
    from ...ops import sparse_grad

    # row-sparse capture (FusedTrainStep lazy-Adam route): when this table
    # is registered in an active capture, the gather routes through a
    # [n_ids, dim] delta so the backward yields row grads, never a
    # vocab-sized scatter-add. Forward value is bit-identical.
    out = sparse_grad.captured_lookup(x, weight)
    if out is None:
        out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return _embedding(x, weight,
                      padding_idx=None if padding_idx is None else int(padding_idx),
                      sparse=bool(sparse))


@op("embedding_bag_op")
def _embedding_bag(x, weight, mode="sum", padding_idx=None):
    from ...ops import sparse_grad

    if padding_idx is None:
        out = sparse_grad.captured_pooled_lookup(x, weight, mode)
        if out is not None:
            return out
        # gather+reduce in one expression: the [B, F, dim] intermediate is
        # never handed to another op, so XLA fuses the lookup and the pool
        # into one loop (verified by the HLO audit on deepfm's first-order
        # term, where the pooled dim is 1)
        rows = jnp.take(weight, x, axis=0)
        return rows.mean(axis=-2) if mode == "mean" else rows.sum(axis=-2)
    # padding rows contribute zero to the sum and do not count toward the
    # mean's denominator (torch.nn.EmbeddingBag semantics)
    out = sparse_grad.captured_lookup(x, weight)
    if out is None:
        out = jnp.take(weight, x, axis=0)
    keep = (x != padding_idx)[..., None]
    out = jnp.where(keep, out, 0.0)
    if mode == "mean":
        n = jnp.maximum(jnp.sum(keep, axis=-2), 1)
        return out.sum(axis=-2) / n.astype(out.dtype)
    return out.sum(axis=-2)


def embedding_bag(x, weight, mode="sum", padding_idx=None, name=None):
    """Fused lookup+pool: ``embedding(x, weight)`` reduced over the field
    axis (``sum`` or ``mean``) without materializing the ``[B, F, dim]``
    intermediate as a separate tensor — the ``F.embedding_bag`` analog.
    ``x`` is int ``[..., F]``; returns ``[..., dim]``."""
    if mode not in ("sum", "mean"):
        raise ValueError(f"embedding_bag mode must be 'sum' or 'mean', "
                         f"got {mode!r}")
    return _embedding_bag(
        x, weight, mode=str(mode),
        padding_idx=None if padding_idx is None else int(padding_idx))


def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _oh

    return _oh(x, num_classes)


@op("label_smooth_op")
def _label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    return _label_smooth(label, prior_dist, epsilon=float(epsilon))


@op("cosine_similarity_op")
def _cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return _cosine_similarity(x1, x2, axis=int(axis), eps=float(eps))


@op("bilinear_op")
def _bilinear(x1, x2, weight, bias=None):
    # weight: [out, in1, in2]
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def bilinear(x1, x2, weight, bias=None, name=None):
    return _bilinear(x1, x2, weight, bias)


@op("interpolate_op")
def _interpolate(x, size=None, mode="nearest", align_corners=False,
                 data_format="NCHW"):
    # channels-first only; convert to jax.image convention
    if data_format in ("NCHW", "NCDHW", "NCW"):
        spatial = x.shape[2:]
        new_shape = (*x.shape[:2], *size)
    else:
        spatial = x.shape[1:-1]
        new_shape = (x.shape[0], *size, x.shape[-1])
    method = {
        "nearest": "nearest",
        "bilinear": "linear",
        "trilinear": "linear",
        "linear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode]
    if align_corners and method != "nearest":
        # jax.image.resize has no align_corners; emulate with explicit gather
        def resize_axis(arr, axis, out_len):
            in_len = arr.shape[axis]
            if out_len == 1 or in_len == 1:
                idx = jnp.zeros((out_len,), jnp.float32)
            else:
                idx = jnp.linspace(0.0, in_len - 1, out_len)
            lo = jnp.floor(idx).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, in_len - 1)
            w = (idx - lo).astype(arr.dtype)
            shape = [1] * arr.ndim
            shape[axis] = out_len
            w = w.reshape(shape)
            return (jnp.take(arr, lo, axis=axis) * (1 - w)
                    + jnp.take(arr, hi, axis=axis) * w)

        out = x
        axes = range(2, x.ndim) if data_format.startswith("NC") else range(1, x.ndim - 1)
        for i, ax in enumerate(axes):
            out = resize_axis(out, ax, new_shape[ax])
        return out
    return jax.image.resize(x, new_shape, method=method)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    if size is None:
        assert scale_factor is not None
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
            [scale_factor] * (x.ndim - 2)
        spatial = x.shape[2:] if data_format.startswith("NC") else x.shape[1:-1]
        size = [int(s * f) for s, f in zip(spatial, sf)]
    if isinstance(size, Tensor):
        size = size.tolist()
    size = tuple(int(s.item() if isinstance(s, Tensor) else s) for s in size)
    return _interpolate(x, size=size, mode=mode, align_corners=bool(align_corners),
                        data_format=data_format)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


@op("pixel_shuffle_op")
def _pixel_shuffle(x, upscale_factor=1, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h * r, w * r, c // (r * r))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _pixel_shuffle(x, upscale_factor=int(upscale_factor),
                          data_format=data_format)


@op("pixel_unshuffle_op")
def _pixel_unshuffle(x, downscale_factor=1, data_format="NCHW"):
    r = downscale_factor
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = x.transpose(0, 1, 3, 5, 2, 4)
    return x.reshape(n, c * r * r, h // r, w // r)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return _pixel_unshuffle(x, downscale_factor=int(downscale_factor),
                            data_format=data_format)


@op("channel_shuffle_op")
def _channel_shuffle(x, groups=1, data_format="NCHW"):
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = x.transpose(0, 2, 1, 3, 4)
    return x.reshape(n, c, h, w)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return _channel_shuffle(x, groups=int(groups), data_format=data_format)


@op("unfold_op")
def _unfold(x, kernel_sizes=(3, 3), strides=(1, 1), paddings=(0, 0, 0, 0),
            dilations=(1, 1)):
    n, c, h, w = x.shape
    kh, kw = kernel_sizes
    sh, sw = strides
    dh, dw = dilations
    pt, pl, pb, pr = paddings
    x = jnp.pad(x, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
    oh = (x.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (x.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, c * kh * kw, oh * ow)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def pair(v, n=2):
        return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n

    pads = pair(paddings, 4)
    if len(pads) == 2:
        pads = (pads[0], pads[1], pads[0], pads[1])
    return _unfold(x, kernel_sizes=pair(kernel_sizes), strides=pair(strides),
                   paddings=pads, dilations=pair(dilations))


@op("fold_op")
def _fold(x, output_sizes=(0, 0), kernel_sizes=(3, 3), strides=(1, 1),
          paddings=(0, 0, 0, 0), dilations=(1, 1)):
    n, ckk, l = x.shape
    kh, kw = kernel_sizes
    c = ckk // (kh * kw)
    oh, ow = output_sizes
    sh, sw = strides
    dh, dw = dilations
    pt, pl, pb, pr = paddings
    ph, pw = oh + pt + pb, ow + pl + pr
    lh = (ph - (dh * (kh - 1) + 1)) // sh + 1
    lw = (pw - (dw * (kw - 1) + 1)) // sw + 1
    cols = x.reshape(n, c, kh, kw, lh, lw)
    out = jnp.zeros((n, c, ph, pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            hi = i * dh
            wj = j * dw
            out = out.at[:, :, hi : hi + sh * lh : sh, wj : wj + sw * lw : sw].add(
                cols[:, :, i, j]
            )
    return out[:, :, pt : pt + oh, pl : pl + ow]


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def pair(v, n=2):
        return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n

    pads = pair(paddings, 4)
    if len(pads) == 2:
        pads = (pads[0], pads[1], pads[0], pads[1])
    return _fold(x, output_sizes=pair(output_sizes), kernel_sizes=pair(kernel_sizes),
                 strides=pair(strides), paddings=pads, dilations=pair(dilations))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


# ---------------------------------------------------------------------------
# round-4 parity additions (reference nn/functional/common.py + extension.py)
# ---------------------------------------------------------------------------

@op("pairwise_distance_op")
def _pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    d = x - y + epsilon
    return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """Reference nn/functional/distance.py pairwise_distance (p-norm of
    x - y along the last dim, epsilon added for gradient stability)."""
    return _pairwise_distance(x, y, p=float(p), epsilon=float(epsilon),
                              keepdim=bool(keepdim))


@op("sequence_mask_op", differentiable=False)
def _sequence_mask(x, maxlen=0):
    return (jnp.arange(maxlen)[None, :]
            < x.reshape(x.shape + (1,))).reshape(x.shape + (maxlen,))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths -> [..., maxlen] 0/1 mask (reference
    nn/functional/extension.py sequence_mask). maxlen=None uses max(x)
    (an eager data-dependent shape, like the reference)."""
    if maxlen is None:
        import numpy as _np

        maxlen = int(_np.asarray(x.numpy()).max())
    out = _sequence_mask(x, maxlen=int(maxlen))
    from ...ops.manipulation import cast

    return cast(out, dtype)


@op("gather_tree_op", differentiable=False)
def _gather_tree(ids, parents):
    """Beam-search backtrace (reference extension.py gather_tree,
    phi/kernels/cpu/gather_tree_kernel.cc): walk parents from the last
    step so each beam column holds its full token path."""
    t, b, k = ids.shape

    def step(beam, tt):
        # beam: [B, K] current beam index per output slot
        tok = jnp.take_along_axis(ids[tt], beam, axis=1)
        par = jnp.take_along_axis(parents[tt], beam, axis=1)
        return par, tok

    beam0 = jnp.broadcast_to(jnp.arange(k, dtype=ids.dtype), (b, k))
    _, toks = jax.lax.scan(step, beam0, jnp.arange(t - 1, -1, -1))
    return toks[::-1]


def gather_tree(ids, parents):
    return _gather_tree(ids, parents)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference
    nn/functional/common.py class_center_sample, single-group form):
    returns (remapped_label, sampled_class_index). Positive classes always
    kept; negatives fill up to num_samples via a seeded permutation."""
    import numpy as _np

    from ...core import rng as _rng
    from ...core.tensor import Tensor as _T

    lab = _np.asarray(label.numpy()).reshape(-1)
    pos = _np.unique(lab)
    rest = _np.setdiff1d(_np.arange(num_classes), pos)
    seed = int(jax.random.randint(_rng.next_key(), (), 0, 2**31 - 1))
    perm = _np.random.RandomState(seed).permutation(rest)
    n_neg = max(int(num_samples) - pos.size, 0)
    sampled = _np.concatenate([pos, perm[:n_neg]])
    remap = _np.full(num_classes, -1, _np.int64)
    remap[sampled] = _np.arange(sampled.size)
    return _T(remap[lab]), _T(sampled.astype(_np.int64))


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention (reference incubate
    nn/functional/sparse_attention.py over a CUDA kernel). TPU-native:
    materialize the CSR pattern as an additive mask over the dense scores —
    XLA fuses the mask into the softmax; the FLOP savings of true block
    sparsity need a Pallas kernel variant of flash_attention (the dense
    flash path is already faster than unfused sparse on v5e; see PERF.md
    for the measurement policy)."""
    import numpy as _np

    offs = _np.asarray(sparse_csr_offset.numpy())
    cols = _np.asarray(sparse_csr_columns.numpy())
    b, h, seq, d = query.shape
    mask = _np.zeros((b, h, seq, seq), _np.float32)
    counts = offs[..., 1:] - offs[..., :-1]            # [b, h, seq]
    for bi in range(offs.shape[0]):                    # b*h scatters only
        for hi in range(offs.shape[1]):
            rows = _np.repeat(_np.arange(seq), counts[bi, hi])
            mask[bi, hi, rows, cols[bi, hi, :rows.size]] = 1.0
    add_mask = (1.0 - mask) * -1e9
    from ...core.tensor import Tensor as _T

    from .flash_attention import _sdpa_ref

    out = _sdpa_ref(
        query.transpose([0, 2, 1, 3]), key.transpose([0, 2, 1, 3]),
        value.transpose([0, 2, 1, 3]), _T(add_mask), None, causal=False,
        dropout=0.0)
    return out.transpose([0, 2, 1, 3])


__all__ += [
    "pairwise_distance", "sequence_mask", "gather_tree",
    "class_center_sample", "sparse_attention",
]
