"""Loss functionals.

Reference: python/paddle/nn/functional/loss.py (cross_entropy at :2399),
PHI kernels cross_entropy_kernel.h etc.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import op
from ...core.tensor import Tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "triplet_margin_loss", "log_loss", "square_error_cost",
    "sigmoid_focal_loss", "ctc_loss", "poisson_nll_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss",
]


def _reduce(x, reduction):
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


@op("cross_entropy_op")
def _cross_entropy(logits, label, weight=None, soft_label=False,
                   ignore_index=-100, reduction="mean", axis=-1,
                   label_smoothing=0.0, use_softmax=True):
    """Materialization-free CE: the log-probability tensor is never formed.

    ``log_softmax`` would write an f32 [N, V] array (2 GB for a 16k-token
    batch at 32k vocab) that the gather then reads once; instead every term
    is a fused reduction over the bf16 logits — max, log-sum-exp, the picked
    logit, and (for smoothing / soft labels) a mean — so HBM sees only
    streaming reads of the logits. ~6 ms/step on the llama-125m bench."""
    lf = logits.astype(jnp.float32)
    if not use_softmax:
        logp = jnp.log(jnp.maximum(lf, 1e-30))
        lse = None  # never read: every lse consumer is behind logp is None
    else:
        logp = None
        m = jnp.max(lf, axis=axis, keepdims=True)
        lse = m + jnp.log(jnp.sum(jnp.exp(lf - m), axis=axis, keepdims=True))
    if soft_label or (label.ndim == logits.ndim and label.shape == logits.shape):
        soft = label.astype(jnp.float32)
        if label_smoothing > 0:
            k = logits.shape[axis]
            soft = soft * (1 - label_smoothing) + label_smoothing / k
        if logp is None:
            # sum(soft * logp) = sum(soft * lf) - lse  (soft sums to 1)
            loss = jnp.squeeze(lse, axis) - jnp.sum(soft * lf, axis=axis)
        else:
            loss = -jnp.sum(soft * logp, axis=axis)
        if weight is not None:
            w = jnp.sum(soft * weight, axis=axis)
            loss = loss * w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.sum(w)
        return _reduce(loss, reduction)
    lab = label
    if lab.ndim == logits.ndim and lab.shape[axis] == 1:
        lab = jnp.squeeze(lab, axis)
    lab = lab.astype(jnp.int32)
    valid = lab != ignore_index
    safe_lab = jnp.where(valid, lab, 0)
    idx = jnp.expand_dims(safe_lab, axis)
    if logp is None:
        picked = jnp.take_along_axis(lf, idx, axis=axis)
        nll = jnp.squeeze(lse - picked, axis)
    else:
        nll = -jnp.take_along_axis(logp, idx, axis=axis).squeeze(axis)
    if label_smoothing > 0:
        k = logits.shape[axis]
        if logp is None:
            mean_logp = jnp.mean(lf, axis=axis) - jnp.squeeze(lse, axis)
        else:
            mean_logp = jnp.mean(logp, axis=axis)
        loss = (1 - label_smoothing) * nll - label_smoothing * mean_logp
    else:
        loss = nll
    if weight is not None:
        w = jnp.take(weight, safe_lab, axis=0).astype(jnp.float32)
        loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
        return _reduce(loss, reduction)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        n_valid = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return jnp.sum(loss) / n_valid
    return _reduce(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True,
                  label_smoothing=0.0, name=None):
    return _cross_entropy(input, label, weight, soft_label=bool(soft_label),
                          ignore_index=int(ignore_index), reduction=reduction,
                          axis=int(axis), label_smoothing=float(label_smoothing),
                          use_softmax=bool(use_softmax))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = _cross_entropy(logits, label, None, soft_label=bool(soft_label),
                          ignore_index=int(ignore_index), reduction="none",
                          axis=int(axis))
    from .activation import softmax as softmax_fn

    loss_keep = loss.unsqueeze(int(axis)) if loss.ndim < logits.ndim else loss
    if return_softmax:
        return loss_keep, softmax_fn(logits, axis=axis)
    return loss_keep


@op("mse_loss_op")
def _mse(input, label, reduction="mean"):
    return _reduce(jnp.square(input - label), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _mse(input, label, reduction=reduction)


def square_error_cost(input, label):
    return _mse(input, label, reduction="none")


@op("l1_loss_op")
def _l1(input, label, reduction="mean"):
    return _reduce(jnp.abs(input - label), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _l1(input, label, reduction=reduction)


@op("nll_loss_op")
def _nll(input, label, weight=None, ignore_index=-100, reduction="mean"):
    lab = label.astype(jnp.int32)
    valid = lab != ignore_index
    safe = jnp.where(valid, lab, 0)
    # input: [N, C, ...]
    idx = jnp.expand_dims(safe, 1)
    picked = -jnp.take_along_axis(input, idx, axis=1).squeeze(1)
    if weight is not None:
        w = jnp.take(weight, safe, axis=0)
        picked = picked * w
        picked = jnp.where(valid, picked, 0.0)
        if reduction == "mean":
            return jnp.sum(picked) / jnp.sum(jnp.where(valid, w, 0.0))
    picked = jnp.where(valid, picked, 0.0)
    if reduction == "mean":
        return jnp.sum(picked) / jnp.maximum(jnp.sum(valid.astype(input.dtype)), 1.0)
    return _reduce(picked, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    return _nll(input, label, weight, ignore_index=int(ignore_index),
                reduction=reduction)


@op("bce_op")
def _bce(input, label, weight=None, reduction="mean"):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps))
             + (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    return _bce(input, label, weight, reduction=reduction)


@op("bce_logits_op")
def _bce_logits(logit, label, weight=None, pos_weight=None, reduction="mean"):
    max_val = jnp.maximum(-logit, 0.0)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    return _bce_logits(logit, label, weight, pos_weight, reduction=reduction)


@op("smooth_l1_op")
def _smooth_l1(input, label, delta=1.0, reduction="mean"):
    diff = jnp.abs(input - label)
    loss = jnp.where(diff < delta, 0.5 * diff * diff / delta, diff - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _smooth_l1(input, label, delta=float(delta), reduction=reduction)


@op("kl_div_op")
def _kl_div(input, label, reduction="mean", log_target=False):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        safe = jnp.maximum(label, 1e-12)
        loss = label * (jnp.log(safe) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    return _kl_div(input, label, reduction=reduction, log_target=bool(log_target))


@op("margin_ranking_op")
def _margin_ranking(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.maximum(-label * (input - other) + margin, 0.0)
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return _margin_ranking(input, other, label, margin=float(margin),
                           reduction=reduction)


@op("hinge_embedding_op")
def _hinge_embedding(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1.0, input, jnp.maximum(margin - input, 0.0))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _hinge_embedding(input, label, margin=float(margin), reduction=reduction)


@op("cosine_embedding_op")
def _cosine_embedding(input1, input2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1), 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    return _cosine_embedding(input1, input2, label, margin=float(margin),
                             reduction=reduction)


@op("triplet_margin_op")
def _triplet(anchor, positive, negative, margin=1.0, p=2.0, eps=1e-6,
             swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + eps, p), axis=-1),
                         1.0 / p)

    d_pos = dist(anchor, positive)
    d_neg = dist(anchor, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.maximum(d_pos - d_neg + margin, 0.0)
    return _reduce(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    return _triplet(input, positive, negative, margin=float(margin), p=float(p),
                    eps=float(epsilon), swap=bool(swap), reduction=reduction)


@op("log_loss_op")
def _log_loss(input, label, epsilon=1e-4):
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(
        1 - input + epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):
    return _log_loss(input, label, epsilon=float(epsilon))


@op("sigmoid_focal_op")
def _sigmoid_focal(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                   reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + jnp.log1p(
        jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * jnp.power(1 - p_t, gamma)
    if alpha >= 0:
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    return _sigmoid_focal(logit, label, normalizer, alpha=float(alpha),
                          gamma=float(gamma), reduction=reduction)


@op("poisson_nll_op")
def _poisson_nll(input, label, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(label + epsilon) - label + 0.5 * jnp.log(
            2 * np.pi * (label + epsilon))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    return _poisson_nll(input, label, log_input=bool(log_input), full=bool(full),
                        epsilon=float(epsilon), reduction=reduction)


@op("soft_margin_op")
def _soft_margin(input, label, reduction="mean"):
    loss = jnp.log1p(jnp.exp(-label * input))
    return _reduce(loss, reduction)


def soft_margin_loss(input, label, reduction="mean", name=None):
    return _soft_margin(input, label, reduction=reduction)


@op("multi_label_soft_margin_op")
def _ml_soft_margin(input, label, weight=None, reduction="mean"):
    loss = -(label * jax.nn.log_sigmoid(input)
             + (1 - label) * jax.nn.log_sigmoid(-input))
    loss = jnp.mean(loss, axis=-1)
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    return _ml_soft_margin(input, label, weight, reduction=reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the classic alpha-recursion in log space (lax.scan over time).
    Reference: paddle warpctc binding (paddle/phi/kernels/gpu/warpctc_kernel.cu)."""

    @op("ctc_loss_op")
    def _ctc(log_probs, labels, input_lengths, label_lengths, blank=0):
        # log_probs: [T, N, C] (paddle convention)
        T, N, C = log_probs.shape
        L = labels.shape[1]
        S = 2 * L + 1
        lab = labels.astype(jnp.int32)
        ext = jnp.full((N, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = -1e30
        lp0 = log_probs[0]
        alpha0 = jnp.full((N, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp0[:, blank])
        alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(lp0, ext[:, 1:2], 1)[:, 0])

        def logaddexp3(a, b, c):
            m = jnp.maximum(jnp.maximum(a, b), c)
            m_safe = jnp.where(m == neg_inf, 0.0, m)
            return jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe)
                           + jnp.exp(c - m_safe)) + m

        same = jnp.concatenate(
            [jnp.zeros((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp):
            a_prev1 = jnp.concatenate(
                [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_prev2 = jnp.concatenate(
                [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_prev2 = jnp.where(same, neg_inf, a_prev2)
            merged = logaddexp3(alpha, a_prev1, a_prev2)
            emit = jnp.take_along_axis(lp, ext, axis=1)
            return merged + emit, None

        def masked_step(carry, inp):
            alpha, t = carry
            lp = inp
            new_alpha, _ = step(alpha, lp)
            keep = (t + 1) < input_lengths  # [N]
            alpha = jnp.where(keep[:, None], new_alpha, alpha)
            return (alpha, t + 1), None

        (alpha, _), _ = jax.lax.scan(masked_step, (alpha0, jnp.zeros((), jnp.int32)),
                                     log_probs[1:])
        end = 2 * label_lengths.astype(jnp.int32)
        a_last = jnp.take_along_axis(alpha, end[:, None], 1)[:, 0]
        a_last2 = jnp.take_along_axis(alpha, jnp.maximum(end - 1, 0)[:, None], 1)[:, 0]
        m = jnp.maximum(a_last, a_last2)
        m_safe = jnp.where(m == neg_inf, 0.0, m)
        ll = jnp.log(jnp.exp(a_last - m_safe) + jnp.exp(a_last2 - m_safe)) + m
        return -ll

    loss = _ctc(log_probs, labels, input_lengths, label_lengths, blank=int(blank))
    if reduction == "mean":
        from ...ops.math import mean as mean_op

        return mean_op(loss / label_lengths.astype(loss.dtype))
    if reduction == "sum":
        from ...ops.math import sum as sum_op

        return sum_op(loss)
    return loss


# ---------------------------------------------------------------------------
# round-4 parity additions (reference python/paddle/nn/functional/loss.py)
# ---------------------------------------------------------------------------

@op("dice_loss")
def _dice_loss(input, label, epsilon=1e-5):
    lab = jax.nn.one_hot(label[..., 0], input.shape[-1], dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = 2.0 * jnp.sum(input * lab, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + jnp.sum(lab, axis=reduce_dims)
    return jnp.mean(1.0 - inter / (union + epsilon))


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Reference loss.py dice_loss: label is int [..., 1] class ids."""
    return _dice_loss(input, label, epsilon=float(epsilon))


@op("npair_loss")
def _npair_loss(anchor, positive, labels, l2_reg=0.002):
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, axis=1))
                    + jnp.mean(jnp.sum(positive * positive, axis=1))) / 2.0
    sim = anchor @ positive.T                           # [B, B]
    lab = labels.reshape(-1)
    same = (lab[:, None] == lab[None, :]).astype(sim.dtype)
    same = same / jnp.sum(same, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(same * logp, axis=1))
    return ce + reg


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    return _npair_loss(anchor, positive, labels, l2_reg=float(l2_reg))


@op("multi_margin_loss_op")
def _multi_margin(input, label, weight=None, p=1, margin=1.0,
                  reduction="mean"):
    n, c = input.shape
    correct = jnp.take_along_axis(input, label.reshape(-1, 1), axis=1)
    diff = jnp.maximum(margin - correct + input, 0.0)
    if p == 2:
        diff = diff * diff
    mask = 1.0 - jax.nn.one_hot(label.reshape(-1), c, dtype=input.dtype)
    loss = jnp.sum(diff * mask, axis=1) / c
    if weight is not None:
        # per-class weight of each sample's TARGET class (torch/reference)
        loss = loss * weight.reshape(-1)[label.reshape(-1)]
    return _reduce(loss, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    return _multi_margin(input, label, weight, p=int(p),
                         margin=float(margin), reduction=reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Reference loss.py triplet_margin_with_distance_loss — user-supplied
    distance callable (defaults to pairwise L2)."""
    from .common import pairwise_distance

    dfn = distance_function or (lambda a, b: pairwise_distance(a, b))
    dp = dfn(input, positive)
    dn = dfn(input, negative)
    if swap:
        from ...ops import math as _m

        dn = _m.minimum(dn, dfn(positive, negative))
    loss = (dp - dn + margin).clip(0.0)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


@op("gaussian_nll_loss_op")
def _gaussian_nll(input, label, variance, full=False, epsilon=1e-6,
                  reduction="mean"):
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + (label - input) ** 2 / var)
    if full:
        loss = loss + 0.5 * jnp.log(jnp.asarray(2.0 * np.pi, input.dtype))
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    return _gaussian_nll(input, label, variance, full=bool(full),
                         epsilon=float(epsilon), reduction=reduction)


@op("hsigmoid_loss_op")
def _hsigmoid(input, label, weight, bias=None, num_classes=2):
    """Default-tree hierarchical sigmoid (reference
    nn/functional/loss.py hsigmoid_loss; phi cpu kernel
    hierarchical_sigmoid_kernel.cc): complete binary tree over class ids,
    code length ceil(log2(C)); internal node index via the heap encoding
    the reference's MatrixBitCodeFunctor uses (node = label + C, walk to
    root, parent = node / 2; code bit = node & 1)."""
    c = num_classes
    depth = max(int(np.ceil(np.log2(c))), 1)
    node = label.reshape(-1).astype(jnp.int32) + c      # heap leaf id
    total = jnp.zeros(input.shape[0], jnp.float32)
    for _ in range(depth):
        parent = node // 2
        bit = (node & 1).astype(jnp.float32)            # 1 -> right child
        active = parent >= 1
        w_idx = jnp.clip(parent - 1, 0, weight.shape[0] - 1)
        logits = jnp.sum(input * weight[w_idx], axis=1)
        if bias is not None:
            logits = logits + bias.reshape(-1)[w_idx]
        # sigmoid CE with target = bit
        term = jax.nn.softplus(logits) - bit * logits
        total = total + jnp.where(active & (parent > 0) & (parent < c),
                                  term, 0.0)
        node = parent
    return jnp.mean(total)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "custom-tree hsigmoid (path_table/path_code) is not supported; "
            "the default complete-binary-tree mode matches the reference")
    return _hsigmoid(input, label, weight, bias, num_classes=int(num_classes))


@op("margin_cross_entropy_op")
def _margin_ce(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
               scale=64.0, return_softmax=False, reduction="mean"):
    """ArcFace/CosFace combined-margin softmax CE (reference
    nn/functional/loss.py margin_cross_entropy; single-group form — the
    model-parallel form shards the class dim over the mp axis via GSPMD)."""
    lab = label.reshape(-1)
    onehot = jax.nn.one_hot(lab, logits.shape[1], dtype=jnp.float32)
    cos = jnp.clip(logits.astype(jnp.float32), -1.0, 1.0)
    theta = jnp.arccos(cos)
    target = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = jnp.where(onehot > 0, target, cos) * scale
    logp = jax.nn.log_softmax(adjusted, axis=1)
    loss = -jnp.sum(onehot * logp, axis=1)
    loss = _reduce(loss, reduction)
    if return_softmax:
        return loss, jax.nn.softmax(adjusted, axis=1)
    return loss


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    return _margin_ce(logits, label, margin1=float(margin1),
                      margin2=float(margin2), margin3=float(margin3),
                      scale=float(scale), return_softmax=bool(return_softmax),
                      reduction=reduction)


@op("rnnt_loss_op")
def _rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
               fastemit_lambda=0.0, reduction="mean"):
    """RNN-Transducer loss (reference nn/functional/loss.py rnnt_loss
    binding warprnnt): forward alpha recursion over the [T, U+1] lattice
    as a lax.scan over T with a cummax-style within-row scan over U —
    static shapes, runs batched on the VPU.

    logits: [B, T, U+1, V] raw (log_softmax applied inside, like warprnnt).
    """
    b, t_max, u1, v = logits.shape
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # emit[b, t, u] = logP(label_{u+1} | t, u);  blank[b, t, u] = logP(blank)
    lab = labels.astype(jnp.int32)                       # [B, U]
    emit = jnp.take_along_axis(
        logp[:, :, :u1 - 1, :],
        lab[:, None, :, None].repeat(t_max, axis=1), axis=3)[..., 0]
    blankp = logp[..., blank]                            # [B, T, U+1]
    NEG = jnp.float32(-1e30)

    t_len = logit_lengths.reshape(-1).astype(jnp.int32)
    u_len = label_lengths.reshape(-1).astype(jnp.int32)

    def row(alpha_prev, t):
        """alpha row at time t from the row at t-1: vertical (blank) moves
        enter from alpha[t-1, u]; horizontal (emit) moves chain along u
        within the row — a sequential prefix recursion (U is small)."""
        from_blank = alpha_prev + blankp[:, t - 1, :]

        def scan_u(bvals):
            from_b, em = bvals

            def cell(c, u):
                val = from_b[u]
                via = c + em[u - 1]
                out = jnp.where(u > 0, jnp.logaddexp(val, via), val)
                return out, out

            _, outs = jax.lax.scan(cell, NEG, jnp.arange(u1))
            return outs

        alpha_t = jax.vmap(scan_u)((from_blank, emit[:, t]))
        return alpha_t, None

    # t = 0 row: only emissions along u
    def scan_u0(bvals):
        def cell(c, u):
            via = c + bvals[u - 1]
            out = jnp.where(u > 0, via, 0.0)
            return out, out
        _, outs = jax.lax.scan(cell, jnp.float32(0.0), jnp.arange(u1))
        return outs

    alpha0 = jax.vmap(scan_u0)(emit[:, 0])
    def step(alpha_prev, t):
        a, _ = row(alpha_prev, t)
        return a, a
    alpha_T, rows = jax.lax.scan(step, alpha0, jnp.arange(1, t_max))
    all_rows = jnp.concatenate([alpha0[None], rows], axis=0)  # [T, B, U+1]
    # total logprob: alpha[t_len-1, u_len] + blank at (t_len-1, u_len)
    bi = jnp.arange(b)
    final_alpha = all_rows[t_len - 1, bi, u_len]
    final = final_alpha + blankp[bi, t_len - 1, u_len]
    loss = -final
    return _reduce(loss, reduction)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """fastemit_lambda is accepted but not applied (plain transducer
    objective; FastEmit regularization is a training heuristic layered on
    the same lattice)."""
    return _rnnt_loss(input, label, input_lengths, label_lengths,
                      blank=int(blank), reduction=reduction)


__all__ += [
    "dice_loss", "npair_loss", "multi_margin_loss",
    "triplet_margin_with_distance_loss", "gaussian_nll_loss",
    "hsigmoid_loss", "margin_cross_entropy", "rnnt_loss",
]
