"""Attention functionals.

Reference: python/paddle/nn/functional/flash_attention.py (flash_attention
at :146, scaled_dot_product_attention at :441) binding third_party/flashattn
CUDA kernels. TPU-native design: a Pallas flash-attention kernel
(paddle_tpu/ops/pallas/flash_attention.py) on TPU backends, with an XLA
reference path (still fused well by XLA) elsewhere. Layout follows paddle:
[batch, seqlen, num_heads, head_dim].
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ...core.dispatch import op
from ...core.tensor import Tensor

__all__ = ["flash_attention", "scaled_dot_product_attention",
           "flash_attn_unpadded", "sdp_kernel", "fused_rope_attention"]


def _use_pallas(q, k=None):
    if jax.default_backend() in ("tpu", "axon"):
        # pallas kernel needs MXU-friendly head_dim (multiple of 64, >= 64)
        # and enough seq to tile; fall back to the XLA path for tiny shapes.
        # The kernel's causal mask is aligned for seq_q == seq_k only, so
        # KV-cache prefill (seq_k > seq_q) takes the XLA path, whose tril
        # mask is bottom-right aligned like the reference.
        if k is not None and q.shape[1] != k.shape[1]:
            return False
        return q.shape[1] >= 128 and q.shape[3] % 64 == 0 and q.shape[3] >= 64
    return False


@op("sdpa_ref")
def _sdpa_ref(q, k, v, attn_mask=None, dropout_key=None, causal=False,
              dropout=0.0, scale=None):
    # [B, S, H, D] -> [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # GQA: broadcast kv heads if fewer than q heads
    hq, hk = qt.shape[1], kt.shape[1]
    if hk != hq:
        rep = hq // hk
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, -1e30)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_key is not None and dropout > 0.0:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


#: which kernel the last scaled_dot_product_attention call used
#: ("pallas" | "xla") — observability so benches/tests can assert the fast
#: path is actually taken instead of trusting the silent fallback
LAST_PATH = None


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """paddle.nn.functional.scaled_dot_product_attention
    (reference flash_attention.py:441)."""
    global LAST_PATH
    from ...core import rng

    dk = None
    if dropout_p > 0.0 and training:
        dk = rng.next_key()
    if _use_pallas(query, key) and attn_mask is None and dropout_p == 0.0:
        try:
            from ...ops.pallas.flash_attention import flash_attention_fwd

            out = flash_attention_fwd(query, key, value,
                                      causal=bool(is_causal))
            LAST_PATH = "pallas"
            return out
        except Exception:
            import warnings

            warnings.warn("Pallas flash-attention kernel failed; using the "
                          "XLA path", stacklevel=2)
    LAST_PATH = "xla"
    return _sdpa_ref(query, key, value, attn_mask, dk, causal=bool(is_causal),
                     dropout=float(dropout_p))


def fused_rope_attention_enabled(batch, seq, heads, head_dim):
    """Cheap pre-projection gate so callers can skip building q/k/v for the
    fused path when it will not be taken (the shapes alone decide)."""
    import os

    if os.environ.get("PT_FUSED_ROPE", "0") != "1":
        return False

    class _S:
        shape = (batch, seq, heads, head_dim)

    return _use_pallas(_S(), _S()) and head_dim % 2 == 0


def fused_rope_attention(query, key, value, cos, sin, is_causal=True,
                         training=True):
    """Rope-fused flash attention: q/k arrive PRE-rotary and the rotation
    runs inside the Pallas kernels (ops/pallas/flash_attention.py), saving
    one HBM round-trip per q/k per layer in forward AND backward. Returns
    None when the fused path is unavailable (caller applies rope + sdpa).

    Analog: the reference's fused rope kernels
    (paddle/phi/kernels/fusion/gpu/fused_rope_grad_kernel.cu,
    fused_multi_transformer_op.cu) bound via incubate.nn.functional."""
    global LAST_PATH
    import os

    # default OFF: on v5e the in-kernel rotation recomputes rope on every
    # (q-block, kv-block) pair in backward, and the measured extra VPU work
    # outweighs the saved HBM round-trips (PERF.md r4 ablation: 120.4k vs
    # 124.9k tok/s on the llama-125m bench). Opt in with PT_FUSED_ROPE=1 —
    # profitable when attention is DMA-bound rather than VPU-bound.
    if os.environ.get("PT_FUSED_ROPE", "0") != "1":
        return None
    if not (_use_pallas(query, key) and query.shape[3] % 2 == 0
            and cos.shape[0] == query.shape[1]):
        return None
    try:
        from ...ops.pallas.flash_attention import flash_attention_rope_fwd

        out = flash_attention_rope_fwd(query, key, value, cos, sin,
                                       causal=bool(is_causal))
        LAST_PATH = "pallas_rope"
        return out
    except Exception:
        import warnings

        warnings.warn("rope-fused Pallas attention failed; using the "
                      "unfused path", stacklevel=2)
        return None


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention (reference :146).
    Returns (out, softmax) like the reference (softmax is None unless
    return_softmax)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal,
                                       training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, training=True,
                        name=None):
    """Varlen API parity: fall back to dense by reshaping (single sequence)."""
    q = query.unsqueeze(0) if query.ndim == 3 else query
    k = key.unsqueeze(0) if key.ndim == 3 else key
    v = value.unsqueeze(0) if value.ndim == 3 else value
    out = scaled_dot_product_attention(q, k, v, None, dropout, causal, training)
    return (out.squeeze(0) if query.ndim == 3 else out), None


class sdp_kernel:
    """Context manager API parity (torch-style backend selection no-op)."""

    def __init__(self, **kwargs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
