"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "RMSNorm", "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under GSPMD/pjit the batch axis is sharded and XLA
    computes global statistics automatically when the reduction spans the
    sharded axis — so plain batch_norm IS sync BN in compiled mode (the
    reference needs a dedicated NCCL kernel: paddle/phi/kernels/gpu/
    sync_batch_norm_kernel.cu)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-first fused RMSNorm (reference incubate rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Power-iteration spectral norm (reference: nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        import numpy as np

        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal

        self.weight_u = self.create_parameter(
            [h], default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp
        import jax

        from ...ops.manipulation import moveaxis, reshape

        w = moveaxis(weight, self._dim, 0)
        mat = reshape(w, [w.shape[0], -1])
        u, v = self.weight_u._data, self.weight_v._data
        m = mat._data
        for _ in range(self._power_iters):
            v = m.T @ u
            v = v / (jnp.linalg.norm(v) + self._epsilon)
            u = m @ v
            u = u / (jnp.linalg.norm(u) + self._epsilon)
        self.weight_u._rebind(u)
        self.weight_v._rebind(v)
        sigma = (u @ m @ v)
        from ...core.tensor import Tensor as T

        return weight / T._wrap(sigma)
