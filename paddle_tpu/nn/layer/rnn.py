"""Recurrent layers: SimpleRNN / LSTM / GRU + cells + generic RNN wrapper.

Reference: python/paddle/nn/layer/rnn.py (SimpleRNNCell :697, LSTMCell :876,
GRUCell :1074, RNN/BiRNN wrappers, RNNBase multi-layer stacks). Gate math
matches the reference exactly (LSTM chunk order i,f,g,o; GRU r,z,c with the
reset gate applied after the hidden matmul; GRU update
h = (h_prev - c) * z + c).

TPU-first design: the per-timestep recurrence is a ``lax.scan`` inside ONE
dispatch op per (layer, direction) — XLA compiles the whole sequence into a
single executable with the gate matmuls on the MXU, instead of the
reference's per-step kernel launches (or cuDNN's fused kernel, which this
scan is the XLA analog of). The generic ``RNN(cell)`` wrapper supports
arbitrary user cells via an unrolled loop, like the reference's non-cuDNN
path.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import op
from ...core.tensor import Tensor
from ..initializer import Uniform
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


@op("rnn_scan")
def _rnn_scan(x, h0, c0, w_ih, w_hh, b_ih, b_hh, mode="lstm",
              reverse=False, time_major=False, activation="tanh"):
    """One recurrent layer over the full sequence.

    x: [B, T, I] (or [T, B, I] when time_major). Returns (ys, h_T, c_T);
    c_T is h_T for non-LSTM modes (uniform arity for the dispatch cache).
    """
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # -> [T, B, I]
    if reverse:
        x = x[::-1]

    def proj(v, w, b):
        out = v @ w.T
        return out + b if b is not None else out

    if mode == "lstm":
        def step(carry, xt):
            h, c = carry
            gates = proj(xt, w_ih, b_ih) + proj(h, w_hh, b_hh)
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        (hT, cT), ys = jax.lax.scan(step, (h0, c0), x)
    elif mode == "gru":
        def step(h, xt):
            xg = proj(xt, w_ih, b_ih)
            hg = proj(h, w_hh, b_hh)
            x_r, x_z, x_c = jnp.split(xg, 3, axis=-1)
            h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(x_r + h_r)
            z = jax.nn.sigmoid(x_z + h_z)
            c = jnp.tanh(x_c + r * h_c)  # reset gate after the matmul
            h_new = (h - c) * z + c
            return h_new, h_new

        hT, ys = jax.lax.scan(step, h0, x)
        cT = hT
    else:  # simple
        def step(h, xt):
            h_new = act(proj(xt, w_ih, b_ih) + proj(h, w_hh, b_hh))
            return h_new, h_new

        hT, ys = jax.lax.scan(step, h0, x)
        cT = hT

    if reverse:
        ys = ys[::-1]
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, hT, cT


class RNNCellBase(Layer):
    """ref rnn.py RNNCellBase: zero-state helper."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return tuple(
                Tensor(np.full((batch,) + tuple(s), init_value, np.float32))
                for s in shape)
        return Tensor(np.full((batch,) + tuple(shape), init_value,
                              np.float32))


def _cell_params(layer, n_gates, input_size, hidden_size, weight_ih_attr,
                 weight_hh_attr, bias_ih_attr, bias_hh_attr):
    std = 1.0 / np.sqrt(hidden_size)
    init = Uniform(-std, std)
    layer.weight_ih = layer.create_parameter(
        [n_gates * hidden_size, input_size], attr=weight_ih_attr,
        default_initializer=init)
    layer.weight_hh = layer.create_parameter(
        [n_gates * hidden_size, hidden_size], attr=weight_hh_attr,
        default_initializer=init)
    layer.bias_ih = (None if bias_ih_attr is False else
                     layer.create_parameter([n_gates * hidden_size],
                                            attr=bias_ih_attr, is_bias=True,
                                            default_initializer=init))
    layer.bias_hh = (None if bias_hh_attr is False else
                     layer.create_parameter([n_gates * hidden_size],
                                            attr=bias_hh_attr, is_bias=True,
                                            default_initializer=init))


class SimpleRNNCell(RNNCellBase):
    """ref rnn.py:697 — h = act(W_ih x + b_ih + W_hh h + b_hh)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        _cell_params(self, 1, input_size, hidden_size, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        from .. import functional as F

        if states is None:
            states = self.get_initial_states(inputs)
        g = F.linear(inputs, self.weight_ih.t(), self.bias_ih) + \
            F.linear(states, self.weight_hh.t(), self.bias_hh)
        h = g.tanh() if self.activation == "tanh" else F.relu(g)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    """ref rnn.py:876 — gates chunked i, f, g, o."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        if proj_size:
            raise NotImplementedError(
                "LSTMCell proj_size (LSTMP hidden projection) is not "
                "implemented; use proj_size=None")
        self.input_size = input_size
        self.hidden_size = hidden_size
        _cell_params(self, 4, input_size, hidden_size, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(
                inputs, ((self.hidden_size,), (self.hidden_size,)))
        h0, c0 = states
        ys, hT, cT = _rnn_scan(
            inputs.unsqueeze(1) if inputs.ndim == 2 else inputs,
            h0, c0, self.weight_ih, self.weight_hh, self.bias_ih,
            self.bias_hh, mode="lstm")
        return hT, (hT, cT)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    """ref rnn.py:1074 — r,z,c; h = (h_prev - c) * z + c."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _cell_params(self, 3, input_size, hidden_size, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        ys, hT, _ = _rnn_scan(
            inputs.unsqueeze(1) if inputs.ndim == 2 else inputs,
            states, states, self.weight_ih, self.weight_hh, self.bias_ih,
            self.bias_hh, mode="gru")
        return hT, hT

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Generic cell wrapper, unrolled over time (ref rnn.py RNN). Works with
    any user cell; the fused-scan fast path lives in SimpleRNN/LSTM/GRU."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, **kwargs):
        from ... import ops

        time_dim = 0 if self.time_major else 1
        T = inputs.shape[time_dim]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for t in steps:
            xt = (inputs[t] if self.time_major else inputs[:, t])
            out, states = self.cell(xt, states, **kwargs)
            outs[t] = out
        stacked = ops.manipulation.stack(outs, axis=time_dim)
        return stacked, states


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (ref rnn.py BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, **kwargs):
        from ... import ops

        fw_states, bw_states = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.fw(inputs, fw_states, **kwargs)
        out_bw, st_bw = self.bw(inputs, bw_states, **kwargs)
        out = ops.manipulation.concat([out_fw, out_bw], axis=-1)
        return out, (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer, optionally bidirectional stack over the fused scan op.
    Parameter naming matches the reference flat convention
    (weight_ih_l{k}[_reverse], ...) for state_dict parity."""

    _mode = "simple"
    _gates = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction in ("bidirectional", "bidirect"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(f"unsupported direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation

        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        G = self._gates
        for layer in range(num_layers):
            in_sz = (input_size if layer == 0
                     else hidden_size * self.num_directions)
            for d in range(self.num_directions):
                sfx = f"l{layer}" + ("_reverse" if d == 1 else "")
                setattr(self, f"weight_ih_{sfx}", self.create_parameter(
                    [G * hidden_size, in_sz], attr=weight_ih_attr,
                    default_initializer=init))
                setattr(self, f"weight_hh_{sfx}", self.create_parameter(
                    [G * hidden_size, hidden_size], attr=weight_hh_attr,
                    default_initializer=init))
                setattr(self, f"bias_ih_{sfx}",
                        None if bias_ih_attr is False else
                        self.create_parameter([G * hidden_size],
                                              attr=bias_ih_attr, is_bias=True,
                                              default_initializer=init))
                setattr(self, f"bias_hh_{sfx}",
                        None if bias_hh_attr is False else
                        self.create_parameter([G * hidden_size],
                                              attr=bias_hh_attr, is_bias=True,
                                              default_initializer=init))

    def _zero_state(self, inputs):
        batch = inputs.shape[0 if not self.time_major else 1]
        n = self.num_layers * self.num_directions
        return Tensor(np.zeros((n, batch, self.hidden_size), np.float32))

    def forward(self, inputs, initial_states=None):
        from .. import functional as F
        from ... import ops

        is_lstm = self._mode == "lstm"
        if initial_states is None:
            h0 = self._zero_state(inputs)
            c0 = self._zero_state(inputs) if is_lstm else h0
        else:
            h0, c0 = (initial_states if is_lstm
                      else (initial_states, initial_states))

        x = inputs
        final_h, final_c = [], []
        for layer in range(self.num_layers):
            outs = []
            for d in range(self.num_directions):
                sfx = f"l{layer}" + ("_reverse" if d == 1 else "")
                idx = layer * self.num_directions + d
                ys, hT, cT = _rnn_scan(
                    x, h0[idx], c0[idx],
                    getattr(self, f"weight_ih_{sfx}"),
                    getattr(self, f"weight_hh_{sfx}"),
                    getattr(self, f"bias_ih_{sfx}"),
                    getattr(self, f"bias_hh_{sfx}"),
                    mode=self._mode, reverse=bool(d),
                    time_major=self.time_major,
                    activation=self.activation)
                outs.append(ys)
                final_h.append(hT)
                final_c.append(cT)
            x = (outs[0] if len(outs) == 1
                 else ops.manipulation.concat(outs, axis=-1))
            if self.dropout > 0 and layer < self.num_layers - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)
        h_n = ops.manipulation.stack(final_h, axis=0)
        if is_lstm:
            c_n = ops.manipulation.stack(final_c, axis=0)
            return x, (h_n, c_n)
        return x, h_n


class SimpleRNN(_RNNBase):
    """ref rnn.py SimpleRNN."""

    _mode = "simple"
    _gates = 1


class LSTM(_RNNBase):
    """ref rnn.py LSTM."""

    _mode = "lstm"
    _gates = 4

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, proj_size=None, name=None):
        if proj_size:
            raise NotImplementedError(
                "LSTM proj_size (LSTMP hidden projection) is not "
                "implemented; use proj_size=None")
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


class GRU(_RNNBase):
    """ref rnn.py GRU."""

    _mode = "gru"
    _gates = 3

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)
