"""nn.Layer — the module base class.

Reference: python/paddle/base/dygraph/layers.py (class Layer). Parameters are
``core.tensor.Parameter`` (stop_gradient=False); sublayers/parameters/buffers
are tracked via __setattr__ like the reference. ``state_dict`` returns live
Tensors; ``set_state_dict`` rebinds values in place (jax arrays are immutable,
so "in place" = handle rebind, keeping optimizer references valid).
"""

from __future__ import annotations

import collections

import numpy as np

from ...core import dtype as dtypes
from ...core import state as _gstate
from ...core.tensor import Parameter, Tensor
from ..initializer import (
    Constant,
    Initializer,
    default_bias_init,
    default_weight_init,
)


class ParamAttr:
    """paddle.ParamAttr analog (python/paddle/base/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True,
                 do_model_average=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"invalid param attr {attr!r}")


_layer_name_counts: dict[str, int] = collections.defaultdict(int)


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        cls = self.__class__.__name__.lower()
        _layer_name_counts[cls] += 1
        object.__setattr__(self, "_full_name", f"{name_scope or cls}_{_layer_name_counts[cls] - 1}")
        object.__setattr__(self, "_dtype", dtypes.convert_dtype(dtype))
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_forward_pre_hooks", collections.OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", collections.OrderedDict())
        object.__setattr__(self, "_hook_id", 0)
        object.__setattr__(self, "_casted_by_pure_fp16", False)

    # ---------------- parameter/buffer management ----------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer or (
            default_bias_init() if is_bias else default_weight_init()
        )
        if isinstance(init, type):
            init = init()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        t = Tensor(np.zeros([], dtype or "float32"), name=name)
        t.persistable = persistable
        return t

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            assert isinstance(parameter, Parameter), type(parameter)
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        assert isinstance(sublayer, Layer) or sublayer is None
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            self._non_persistable_buffer_names.discard(name)
        return tensor

    # ---------------- attribute routing ----------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            layers is not None and layers.pop(name, None)
            self.__dict__.pop(name, None)
            return
        if isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            params is not None and params.pop(name, None)
            self.__dict__.pop(name, None)
            return
        if params is not None and name in params:
            if value is None or isinstance(value, Tensor):
                params[name] = value
                return
            del params[name]
        if layers is not None and name in layers:
            del layers[name]
        if buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
                return
            del buffers[name]
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # ---------------- traversal ----------------
    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if l is None or id(l) in layers_set:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=p, include_self=True,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{lp}.{name}" if lp else name), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers
            else [(prefix, self)]
        )
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{lp}.{name}" if lp else name), b

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def full_name(self):
        return self._full_name

    # ---------------- modes ----------------
    def train(self):
        for l in self.sublayers(include_self=True):
            object.__setattr__(l, "training", True)
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            object.__setattr__(l, "training", False)
        return self

    # ---------------- state dict ----------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            dest[name] = p
        # persistable buffers
        layers = self.named_sublayers(prefix=structured_name_prefix, include_self=True)
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or name in layer._non_persistable_buffer_names:
                    continue
                dest[(f"{lp}.{name}" if lp else name)] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v._data if isinstance(v, Tensor) else np.asarray(v)
                import jax.numpy as jnp

                t._rebind(jnp.asarray(arr, t.dtype).reshape(t._data.shape))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---------------- dtype conversion ----------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_params(dtypes.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast_params(dtypes.convert_dtype(dtype))
        return self

    def _cast_params(self, dtype, only_float=True):
        import jax.numpy as jnp

        for l in self.sublayers(include_self=True):
            object.__setattr__(l, "_dtype", dtype)
            for p in list(l._parameters.values()) + list(l._buffers.values()):
                if p is None:
                    continue
                if only_float and not dtypes.is_floating_point(p.dtype):
                    continue
                p._rebind(jnp.asarray(p._data, dtype))

    def float(self):
        self._cast_params(dtypes.float32)
        return self

    def bfloat16(self):
        self._cast_params(dtypes.bfloat16)
        return self

    def half(self):
        self._cast_params(dtypes.float16)
        return self

    # ---------------- hooks ----------------
    def register_forward_pre_hook(self, hook):
        hid = self._hook_id
        object.__setattr__(self, "_hook_id", hid + 1)
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = self._hook_id
        object.__setattr__(self, "_hook_id", hid + 1)
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # ---------------- call ----------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            child = repr(l).split("\n")
            child = [child[0]] + ["  " + c for c in child[1:]]
            lines.append(f"  ({name}): " + "\n".join(child))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
