"""nn.utils (reference: python/paddle/nn/utils/)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["parameters_to_vector", "vector_to_parameters", "weight_norm",
           "remove_weight_norm", "spectral_norm"]


def parameters_to_vector(parameters, name=None):
    arrs = [jnp.ravel(p._data) for p in parameters]
    return Tensor._wrap(jnp.concatenate(arrs))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p._rebind(vec._data[offset : offset + n].reshape(p._data.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| (reference nn/utils/weight_norm_hook.py)."""
    from ...core.tensor import Parameter

    weight = getattr(layer, name)
    w = weight._data
    if dim is None:
        norm = jnp.linalg.norm(w)
    else:
        axes = tuple(i for i in range(w.ndim) if i != dim)
        norm = jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=False))
    g = Parameter(norm)
    v = Parameter(w)
    delattr(layer, name)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)

    def hook(layer_, inputs):
        import jax

        vv = getattr(layer_, name + "_v")
        gg = getattr(layer_, name + "_g")
        if dim is None:
            w_new = vv * (gg / (jnp.linalg.norm(vv._data) + 1e-12))
        else:
            axes = tuple(i for i in range(vv._data.ndim) if i != dim)
            from ...ops import math as _m

            norm_v = jnp.sqrt(jnp.sum(jnp.square(vv._data), axis=axes,
                                      keepdims=True))
            shape = [1] * vv._data.ndim
            shape[dim] = -1
            w_new = vv * Tensor._wrap(gg._data.reshape(shape) / (norm_v + 1e-12))
        object.__setattr__(layer_, "_" + name + "_computed", w_new)
        layer_._buffers[name] = w_new

    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    layer._weight_norm_hook_name = name
    return layer


def remove_weight_norm(layer, name="weight"):
    from ...core.tensor import Parameter

    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    w = layer._buffers.get(name)
    delattr(layer, name + "_g")
    delattr(layer, name + "_v")
    layer._buffers.pop(name, None)
    layer.add_parameter(name, Parameter(w._data if w is not None else v._data))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    from ..layer.norm import SpectralNorm

    weight = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = SpectralNorm(list(weight._data.shape), dim=dim,
                      power_iters=n_power_iterations, epsilon=eps)
    layer.add_sublayer(name + "_sn", sn)
    orig = weight

    def hook(layer_, inputs):
        w = sn(orig)
        layer_._buffers[name] = w

    from ...core.tensor import Parameter

    delattr(layer, name)
    layer.add_parameter(name + "_orig", orig)
    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer
