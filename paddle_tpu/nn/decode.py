"""Beam-search decoding: BeamSearchDecoder + dynamic_decode.

Reference: python/paddle/nn/decode.py (Decoder protocol :40,
BeamSearchDecoder :121, dynamic_decode :~780). TPU-native shape: the decode
loop is an eager Python loop over steps (decode lengths are data-dependent;
the reference's static while_loop form exists for export — here generation
is the eager/`jit.save` path, same policy as LlamaForCausalLM.generate).
Beam bookkeeping (top-k over beam*vocab, parent backtrace via
``F.gather_tree``) is expressed in framework ops so it runs on device.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu.nn.functional as F

from ..core.tensor import Tensor
from .. import ops as _ops

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """Wraps an RNN cell into a beam-search Decoder.

    cell(inputs, states) -> (outputs, new_states); ``embedding_fn`` maps
    token ids to cell inputs; ``output_fn`` maps cell outputs to vocab
    logits (identity if the cell already emits logits).
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- reference helpers (decode.py BeamSearchDecoder.tile_beam_merge_...)
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] by repeating each batch row."""
        reps = [1] * (x.ndim + 1)
        reps[1] = beam_size
        tiled = _ops.manipulation.tile(x.unsqueeze(1), reps)
        return tiled.reshape([-1] + list(x.shape[1:]))

    def _merge(self, x):
        return x.reshape([-1] + list(x.shape[2:]))

    def _split(self, x):
        return x.reshape([-1, self.beam_size] + list(x.shape[1:]))

    def initialize(self, initial_cell_states):
        cell_states = initial_cell_states
        flat = cell_states if isinstance(cell_states, (tuple, list)) \
            else [cell_states]
        batch = flat[0].shape[0]
        k = self.beam_size
        cell_states = [self.tile_beam_merge_with_batch(s, k) for s in flat]
        # only beam 0 is live at t=0 (reference kInitialValue -inf trick)
        lp0 = np.full((batch, k), -1e9, np.float32)
        lp0[:, 0] = 0.0
        beam_state = {
            "cell_states": cell_states,
            "log_probs": Tensor(lp0),
            "finished": Tensor(np.zeros((batch, k), np.bool_)),
            "lengths": Tensor(np.zeros((batch, k), np.int64)),
        }
        ids = Tensor(np.full((batch, k), self.start_token, np.int64))
        return ids, beam_state

    def step(self, time, inputs, states):
        """inputs: [B, K] token ids -> (beam_ids [B,K], parent_ids [B,K],
        next_states)."""
        k = self.beam_size
        batch = inputs.shape[0]
        flat_ids = self._merge(inputs)                   # [B*K]
        cell_in = (self.embedding_fn(flat_ids) if self.embedding_fn
                   else flat_ids)
        outputs, next_cell = self.cell(cell_in, states["cell_states"])
        logits = self.output_fn(outputs) if self.output_fn else outputs
        vocab = logits.shape[-1]
        logp = F.log_softmax(logits.astype("float32"), axis=-1)
        logp = self._split(logp)                         # [B, K, V]

        # finished beams only extend with end_token at prob 0
        fin = states["finished"]
        noext = np.full((vocab,), -1e9, np.float32)
        noext[self.end_token] = 0.0
        logp = _ops.where(fin.unsqueeze(-1), Tensor(noext), logp)

        total = states["log_probs"].unsqueeze(-1) + logp  # [B, K, V]
        flat_total = total.reshape([batch, k * vocab])
        top_v, top_i = _ops.manipulation.topk(flat_total, k, axis=-1)
        parent = top_i // vocab                          # [B, K]
        token = top_i % vocab

        # gather beam state by parent
        def pick(x):
            xs = self._split(x)                          # [B, K, ...]
            picked = _ops.manipulation.take_along_axis(
                xs, parent.reshape([batch, k] + [1] * (xs.ndim - 2))
                .expand([batch, k] + list(xs.shape[2:])), axis=1)
            return self._merge(picked)

        next_cell = [pick(s) for s in (next_cell if isinstance(
            next_cell, (tuple, list)) else [next_cell])]
        fin_p = _ops.manipulation.take_along_axis(fin, parent, axis=1)
        len_p = _ops.manipulation.take_along_axis(states["lengths"], parent,
                                                  axis=1)
        now_fin = fin_p | (token == self.end_token)
        new_len = len_p + (~now_fin).astype("int64")
        next_state = {
            "cell_states": next_cell,
            "log_probs": top_v,
            "finished": now_fin,
            "lengths": new_len,
        }
        return token, parent, next_state


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run the decoder to completion (reference decode.py dynamic_decode).

    Returns (ids, final_states) with ids [B, K, T] (or time-major
    [T, B, K]); with return_length, appends the per-beam lengths.
    """
    max_steps = int(max_step_num or 64)
    inputs, state = decoder.initialize(inits)
    step_ids, step_parents = [], []
    for t in range(max_steps):
        token, parent, state = decoder.step(t, inputs, state)
        step_ids.append(token)
        step_parents.append(parent)
        inputs = token
        if bool(state["finished"].numpy().all()):
            break
    ids = _ops.manipulation.stack(step_ids, axis=0)      # [T, B, K]
    parents = _ops.manipulation.stack(step_parents, axis=0)
    traced = F.gather_tree(ids, parents)                 # [T, B, K]
    if not output_time_major:
        traced = traced.transpose([1, 2, 0])             # [B, K, T]
    if return_length:
        return traced, state, state["lengths"]
    return traced, state
